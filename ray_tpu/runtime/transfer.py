"""Pipelined, multi-source chunked object transfer.

(reference: src/ray/object_manager/pull_manager.h:50 — windowed chunk
requests with admission control; push_manager.h:28 — pipelined chunked
pushes; object_buffer_pool.h:32 — chunk assembly into store buffers.
The reference streams 5 MiB chunks one-at-a-time per transfer but keeps
many transfers in flight; here one transfer pipelines a window of chunk
requests and stripes them across every node known to hold a copy, so a
single large pull saturates the link — and a broadcast's later pullers
fan in from the nodes that already finished.)

Used by the core worker's pull path and the node daemon's prefetch
(broadcast relay) path.
"""

from __future__ import annotations

import asyncio

from ray_tpu._private import rpc
from ray_tpu.exceptions import GetTimeoutError, ObjectLostError

CHUNK_BYTES = 5 * 1024 * 1024  # object_manager_default_chunk_size
WINDOW = 8  # in-flight chunk requests per transfer


async def connect_sources(
    holders,
    primary: str | None,
    self_addr: str | None,
    dial,
    fallback=None,
) -> tuple[list, dict]:
    """Dial every candidate holder in parallel and fast-fail the dead.

    Merges ``primary`` + registered ``holders`` (skipping ``self_addr``
    — our own store already missed), dials them concurrently via
    ``dial(addr)``, and appends ``fallback`` (usually the owner's own
    connection) as a last-resort source so evicted/stale holder sets
    can never lose an object the owner still serves. Returns
    ``(conns, addr_by_conn)``; the mapping lets callers report dead
    holders back to the owner's location directory.
    """
    addrs = []
    if primary and primary != self_addr:
        addrs.append(primary)
    for h in holders or ():
        if h != self_addr and h not in addrs:
            addrs.append(h)
    results = await asyncio.gather(
        *(dial(a) for a in addrs), return_exceptions=True
    )
    conns, addr_by_conn = [], {}
    for a, r in zip(addrs, results):
        if isinstance(r, BaseException):
            continue
        conns.append(r)
        addr_by_conn[r] = a
    if fallback is not None and fallback not in conns:
        conns.append(fallback)
    return conns, addr_by_conn


async def pull_object(
    oid_hex: str,
    conns: list,
    timeout: float | None = None,
    chunk_bytes: int = CHUNK_BYTES,
    window: int = WINDOW,
    failed: set | None = None,
) -> tuple[bytes, list[bytes]]:
    """Fetch a store-resident object's segments from one or more holders.

    Returns ``(inband, buffers)``. Chunk requests are pipelined (up to
    ``window`` in flight) and striped round-robin across ``conns``; a
    chunk that fails on one holder (dead connection, evicted copy) is
    retried on the others. ``timeout`` bounds the WHOLE pull. Callers
    passing ``failed`` receive the connections that proved dead or
    copyless — report them to the owner's location directory.
    """
    if not conns:
        raise ObjectLostError(f"object {oid_hex[:12]}…: no holders to pull")
    loop = asyncio.get_running_loop()
    deadline = None if timeout is None else loop.time() + timeout

    def remaining():
        if deadline is None:
            return None
        left = deadline - loop.time()
        if left <= 0:
            raise GetTimeoutError(f"timed out pulling {oid_hex[:12]}…")
        return left

    # Meta from the first holder that answers; the rest may be stale.
    meta = None
    dead: set = set()
    for c in conns:
        try:
            m = await asyncio.wait_for(
                c.call("get_object_meta", oid_hex=oid_hex), remaining()
            )
        except asyncio.TimeoutError:
            raise GetTimeoutError(f"timed out pulling {oid_hex[:12]}…")
        except (rpc.ConnectionLost, rpc.RpcError):
            dead.add(c)
            continue
        if m.get("ok"):
            meta = m
            break
        dead.add(c)
    if meta is None:
        raise ObjectLostError(
            f"object {oid_hex[:12]}… vanished from every holder's store"
        )
    total = meta["total"]
    offsets = list(range(0, total, chunk_bytes))
    # Preallocate the segment buffers and write each arriving chunk
    # straight into place — assembling via a parts list + join + slice
    # would add ~3 object-sized transient copies per pull (reference:
    # object_buffer_pool.h writes chunks into the plasma buffer
    # directly for the same reason).
    seg_lens = meta["seg_lens"]
    segs = [bytearray(n) for n in seg_lens]
    seg_starts = []
    pos = 0
    for n in seg_lens:
        seg_starts.append(pos)
        pos += n

    def place(off: int, data: bytes):
        dpos = 0
        for start, buf in zip(seg_starts, segs):
            end = start + len(buf)
            if off + len(data) <= start or off >= end:
                continue
            s = max(off, start)
            e = min(off + len(data), end)
            memoryview(buf)[s - start : e - start] = memoryview(data)[
                s - off : e - off
            ]
            dpos += e - s
        return dpos

    sem = asyncio.Semaphore(window)

    async def fetch(i: int, off: int):
        async with sem:
            start = i % len(conns)
            order = conns[start:] + conns[:start]
            last_err: Exception | None = None
            for c in order:
                if c in dead:
                    continue
                try:
                    r = await asyncio.wait_for(
                        c.call(
                            "get_object_chunk",
                            oid_hex=oid_hex,
                            offset=off,
                            size=min(chunk_bytes, total - off),
                        ),
                        remaining(),
                    )
                except asyncio.TimeoutError:
                    raise GetTimeoutError(
                        f"timed out pulling {oid_hex[:12]}…"
                    )
                except (rpc.ConnectionLost, rpc.RpcError) as e:
                    dead.add(c)
                    last_err = e
                    continue
                if r.get("ok"):
                    place(off, r["data"])
                    return
                last_err = ObjectLostError(
                    f"object {oid_hex[:12]}… evicted from a holder "
                    "mid-pull"
                )
            raise last_err or ObjectLostError(
                f"object {oid_hex[:12]}… pull failed on every holder"
            )

    # return_exceptions: let in-flight siblings finish/fail on their own
    # (bounded by the shared deadline) instead of orphaning them, then
    # surface the first failure.
    results = await asyncio.gather(
        *(fetch(i, off) for i, off in enumerate(offsets)),
        return_exceptions=True,
    )
    if failed is not None:
        failed.update(dead)
    for r in results:
        if isinstance(r, BaseException):
            raise r
    # inband must be bytes (pickle stream); payload buffers stay as the
    # preallocated bytearrays (writable buffers deserialize zero-copy).
    return bytes(segs[0]), segs[1:]
