"""Node manager: per-host daemon — worker pool + lease scheduling.

Mirrors the reference raylet's local responsibilities (reference:
src/ray/raylet/node_manager.h:140 `HandleRequestWorkerLease`,
worker_pool.h:280): it spawns/caches Python worker processes, grants
worker leases against local resource accounting, queues infeasible
requests, reaps dead workers, and owns the node's shared-memory object
store directory. TPU twist: TPU chips are first-class resources — the
node detects them from the JAX runtime / environment and registers
"TPU" alongside "CPU" (reference handles TPU via a Python plugin,
python/ray/_private/accelerators/tpu.py).
"""

from __future__ import annotations

import asyncio
import collections
import hashlib
import json
import logging
import os
import subprocess
import sys
import tempfile
import time
from pathlib import Path
from typing import Any

from ray_tpu._private import rpc
from ray_tpu._private.ids import NodeID, WorkerID

logger = logging.getLogger(__name__)

IDLE_WORKER_CAP = 4  # idle processes kept warm per node
SPAWN_TIMEOUT_S = 30.0
PENDING_SPILL_S = 2.0  # queued lease age before bouncing to spillback


_mem_frac_cache: "tuple[float, float]" = (-1.0, 0.0)  # (ts, value)


def system_memory_fraction() -> float:
    """Fraction of system memory in use, cgroup-aware like the
    reference's MemoryMonitor (reference: memory_monitor.h:52 reads
    cgroup limits before /proc/meminfo). Test override:
    RAY_TPU_FAKE_MEMORY_FRAC_FILE names a file holding a float.

    Cached process-wide for 200 ms: parsing /proc/meminfo costs ~1 ms
    and every node-manager loop (memory monitor, spill) polls it — at
    scale-simulation density (hundreds of NodeManagers per process)
    the uncached reads alone ate ~7% of the core (PROFILE_r05.md)."""
    import time as _time

    from ray_tpu._private import config

    fake = config.get("FAKE_MEMORY_FRAC_FILE")
    if fake:
        try:
            with open(fake) as f:
                return float(f.read().strip())
        except (OSError, ValueError):
            return 0.0
    global _mem_frac_cache
    ts, cached = _mem_frac_cache
    now = _time.monotonic()
    if now - ts < 0.2:
        return cached
    value = _read_memory_fraction()
    _mem_frac_cache = (now, value)
    return value


def _read_memory_fraction() -> float:
    # cgroup v2 (container limits beat host totals)
    try:
        with open("/sys/fs/cgroup/memory.max") as f:
            limit = f.read().strip()
        if limit != "max":
            with open("/sys/fs/cgroup/memory.current") as f:
                current = float(f.read().strip())
            return current / float(limit)
    except (OSError, ValueError):
        pass
    try:
        info = {}
        with open("/proc/meminfo") as f:
            for line in f:
                parts = line.split()
                info[parts[0].rstrip(":")] = float(parts[1])
        total = info["MemTotal"]
        avail = info.get("MemAvailable", info.get("MemFree", total))
        return 1.0 - avail / total
    except (OSError, KeyError, ValueError):
        return 0.0


def worker_rss_bytes(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


def _spill_watermarks() -> tuple[float, float]:
    """Object-spilling watermarks (fractions of store capacity): above
    HIGH the daemon moves cold objects to disk until usage drops below
    LOW (reference: LocalObjectManager triggers spilling at
    object_spilling_threshold, local_object_manager.h:44). Read per
    tick so per-process overrides apply."""
    from ray_tpu._private import config

    return (config.get("SPILL_HIGH"), config.get("SPILL_LOW"))


# path → (monotonic ts, fingerprint). Short TTL: env_hash runs per
# lease, a full tree walk every time would tax hot paths, but an edited
# working_dir must be picked up within seconds.
_fp_cache: dict[str, tuple[float, str]] = {}


def _dir_fingerprint(path: str, ttl: float = 5.0) -> str:
    """Content fingerprint of a directory tree (names, sizes, mtimes) —
    the reference content-hashes working_dir packages so edited trees
    re-stage instead of silently serving stale copies."""
    now = time.monotonic()
    hit = _fp_cache.get(path)
    if hit and now - hit[0] < ttl:
        return hit[1]
    h = hashlib.sha1()
    for dirpath, dirnames, filenames in sorted(os.walk(path)):
        dirnames.sort()
        for fn in sorted(filenames):
            p = os.path.join(dirpath, fn)
            try:
                st = os.stat(p)
            except OSError:
                continue
            h.update(
                f"{os.path.relpath(p, path)}:{st.st_size}:"
                f"{st.st_mtime_ns}\n".encode()
            )
    fp = h.hexdigest()[:12]
    _fp_cache[path] = (now, fp)
    return fp


def env_hash(runtime_env: dict | None) -> str:
    """Stable key for a runtime_env: workers are pooled per distinct env
    (reference: runtime_env workers are dedicated + cached by env hash,
    python/ray/_private/runtime_env/). working_dir envs hash the tree's
    CONTENT, so an edit re-stages and re-pools instead of reusing
    workers running stale code."""
    if not runtime_env:
        return ""
    key = dict(runtime_env)
    wd = key.get("working_dir")
    if wd:
        key["working_dir_fp"] = _dir_fingerprint(os.path.expanduser(wd))
    return hashlib.sha1(
        json.dumps(key, sort_keys=True).encode()
    ).hexdigest()[:16]


import threading

_ENV_CACHE_ROOT = os.path.join(tempfile.gettempdir(), "ray_tpu-envs")
_built_envs: dict[str, dict] = {}  # env hash → {"python": ..., "cwd": ...}
# Created at import: lazy creation would itself race between the first
# two concurrent builds.
_env_build_lock = threading.Lock()


def _locked_env_delete(h: str, root: str):
    """GC deletion under the SAME per-hash flock build_runtime_env
    takes: a concurrent rebuild of the just-evicted hash either waits
    for the delete to finish (then rebuilds from a clean slate) or
    holds the lock first (then the marker it wrote stays intact —
    this delete re-checks and aborts)."""
    import fcntl
    import shutil as _shutil

    os.makedirs(_ENV_CACHE_ROOT, exist_ok=True)
    with open(os.path.join(_ENV_CACHE_ROOT, f".{h}.lock"), "w") as lf:
        fcntl.flock(lf, fcntl.LOCK_EX)
        try:
            if h in _built_envs:
                # A rebuild re-registered this hash while the delete
                # was queued: the tree is live again, leave it.
                return
            _shutil.rmtree(root, ignore_errors=True)
        finally:
            fcntl.flock(lf, fcntl.LOCK_UN)


def _make_env_cache():
    from ray_tpu._private import config
    from ray_tpu.runtime.runtime_env import UriCache

    # Evicted envs must also leave the build memo, or the next request
    # would hand out a python/cwd whose files were just deleted.
    return UriCache(
        config.get("ENV_CACHE_BYTES"),
        on_evict=lambda h: _built_envs.pop(h, None),
        delete_fn=_locked_env_delete,
    )


_env_cache = _make_env_cache()


def build_runtime_env(runtime_env: dict, h: str | None = None) -> dict:
    """Materialize a task/actor runtime env on this node: a venv for
    ``pip`` dependencies and a staged copy of ``working_dir``. Cached by
    env hash — the content-addressed URI-cache equivalent (reference:
    the per-node runtime_env agent builds pip/conda envs,
    _private/runtime_env/agent/runtime_env_agent.py, uri_cache.py).

    Offline clusters (no egress) install from local wheels:
    ``{"pip": [...], "pip_no_index": True, "pip_find_links": dir}``.
    """
    if h is None:
        h = env_hash(runtime_env)  # content-aware for working_dir envs
    if h in _built_envs:
        return _built_envs[h]
    with _env_build_lock:
        if h in _built_envs:
            return _built_envs[h]
        info: dict = {"python": None, "cwd": None}
        root = os.path.join(_ENV_CACHE_ROOT, h)
        # Cross-PROCESS exclusion too (several node daemons share one
        # host and one env cache): a file lock per env hash.
        os.makedirs(_ENV_CACHE_ROOT, exist_ok=True)
        import fcntl

        lock_f = open(os.path.join(_ENV_CACHE_ROOT, f".{h}.lock"), "w")
        # tpulint: allow(blocking-under-lock reason=thread lock plus file lock together are the design - one env build per thread AND per host; builds are expected to take seconds)
        fcntl.flock(lock_f, fcntl.LOCK_EX)
        try:
            _build_env_locked(runtime_env, root, info)
        finally:
            # tpulint: allow(blocking-under-lock reason=unlock of the cross-process file lock cannot block)
            fcntl.flock(lock_f, fcntl.LOCK_UN)
            lock_f.close()
        _built_envs[h] = info
        if os.path.isdir(root):
            # Only on-disk builds participate in byte-budget GC (named
            # conda envs and pure env_vars envs occupy no cache space).
            _env_cache.register(h, root)
        return info


def _build_env_locked(runtime_env: dict, root: str, info: dict) -> None:
    import shutil as _shutil

    pip_pkgs = runtime_env.get("pip")
    uv_pkgs = runtime_env.get("uv")
    conda_spec = runtime_env.get("conda")
    if sum(map(bool, (pip_pkgs, uv_pkgs, conda_spec))) > 1:
        raise ValueError(
            "runtime_env: 'pip', 'uv', 'conda' are mutually exclusive — "
            "specify one package manager, not both"
        )
    if conda_spec:
        from ray_tpu.runtime.runtime_env import build_conda_env

        info["python"] = build_conda_env(conda_spec, root)
    if pip_pkgs or uv_pkgs:
        venv_dir = os.path.join(root, "venv")
        vpython = os.path.join(venv_dir, "bin", "python")
        marker = os.path.join(venv_dir, ".ready")
        have_uv = _shutil.which("uv") is not None
        use_uv = bool(uv_pkgs) and have_uv
        if uv_pkgs and not have_uv:
            # Degrade to pip with the same package list rather than
            # fail the lease on hosts without the uv binary — LOUDLY:
            # pip's resolver can pin different versions for the same
            # specs, so heterogeneous clusters would otherwise build
            # divergent envs under one env hash with no trace.
            print(
                f"ray_tpu runtime_env: uv binary not found on this "
                f"node; building {uv_pkgs} with pip instead (resolver "
                f"may differ across nodes)",
                flush=True,
            )
            pip_pkgs = uv_pkgs
        if not os.path.exists(marker):
            os.makedirs(root, exist_ok=True)
            # --clear / fresh dir: a crash mid-build leaves no marker;
            # rebuild from scratch. system-site-packages: jax & friends
            # come from the image, only the requested deps layer on.
            if use_uv:
                # uv venv has no --clear: remove and recreate.
                _shutil.rmtree(venv_dir, ignore_errors=True)
                proc = subprocess.run(
                    [
                        "uv", "venv", "--system-site-packages",
                        "--python", sys.executable, venv_dir,
                    ],
                    capture_output=True,
                    text=True,
                )
                if proc.returncode != 0:
                    raise RuntimeError(
                        f"runtime_env uv venv failed:\n{proc.stderr[-2000:]}"
                    )
                cmd = ["uv", "pip", "install", "--python", vpython]
            else:
                subprocess.run(
                    [
                        sys.executable, "-m", "venv", "--clear",
                        "--system-site-packages", venv_dir,
                    ],
                    check=True,
                    capture_output=True,
                )
                cmd = [vpython, "-m", "pip", "install",
                       "--no-warn-script-location"]
            if runtime_env.get("pip_no_index"):
                cmd.append("--no-index")
            if runtime_env.get("pip_find_links"):
                cmd += ["--find-links", runtime_env["pip_find_links"]]
            cmd += list(uv_pkgs if use_uv else pip_pkgs)
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"runtime_env {'uv' if use_uv else 'pip'} install "
                    f"failed:\n{proc.stderr[-2000:]}"
                )
            with open(marker, "w") as f:
                f.write("ok")
        info["python"] = vpython
    working_dir = runtime_env.get("working_dir")
    if working_dir:
        import shutil

        stage = os.path.join(root, "workdir")
        if not os.path.isdir(stage):
            os.makedirs(root, exist_ok=True)
            tmp = f"{stage}.staging-{os.getpid()}"
            shutil.rmtree(tmp, ignore_errors=True)
            shutil.copytree(os.path.expanduser(working_dir), tmp)
            os.rename(tmp, stage)
        info["cwd"] = stage


def detect_resources() -> dict[str, float]:
    """Detect node resources WITHOUT initializing a JAX backend: grabbing
    jax.devices() here would lock the TPU chip into the daemon process
    (and hang if another process holds the tunnel). Accelerators come
    from the plugin registry (reference: per-vendor AcceleratorManagers,
    python/ray/_private/accelerators/)."""
    from ray_tpu._private.accelerators import detect_accelerator_resources

    resources: dict[str, float] = {"CPU": float(os.cpu_count() or 1)}
    resources.update(detect_accelerator_resources())
    return resources


class Lease:
    __slots__ = (
        "lease_id", "worker", "resources", "actor", "bundle",
        "bundle_resources", "granted_at",
    )

    def __init__(self, lease_id: str, worker: dict, resources: dict, actor: bool):
        self.lease_id = lease_id
        self.worker = worker
        self.resources = resources
        self.actor = actor
        self.bundle: tuple | None = None  # (pg_id, index) if bundle-backed
        self.bundle_resources: dict | None = None
        self.granted_at = time.monotonic()


class NodeManager:
    def __init__(
        self,
        head_addr: str,
        store_dir: str,
        resources: dict[str, float] | None = None,
        worker_env: dict[str, str] | None = None,
        labels: dict[str, str] | None = None,
    ):
        self.node_id = NodeID.random().hex()
        self.head_addr = head_addr
        self.store_dir = store_dir
        self.total = resources or detect_resources()
        self.available = dict(self.total)
        self.labels = detect_labels() if labels is None else dict(labels)
        self.worker_env = worker_env or {}
        self.server = rpc.Server(self._handle)
        self.addr: str | None = None
        self.head: rpc.Connection | None = None
        # worker_id → {proc, conn, addr, pid, state: spawning|idle|leased}
        self.workers: dict[str, dict] = {}
        # env_hash → idle worker ids (workers are pooled per runtime_env)
        self.idle: dict[str, list[str]] = collections.defaultdict(list)
        self.leases: dict[str, Lease] = {}
        # (resources, actor, fut, enqueued_at, runtime_env): queued
        # feasible-but-unavailable lease requests. Entries older than
        # PENDING_SPILL_S are bounced with retry_spill so the caller can
        # try another node via the head (lease spillback) instead of
        # camping here while new capacity sits idle elsewhere.
        self._pending: list[tuple] = []
        # (pg_id, index) → {"total": resources, "available": resources}
        self.bundles: dict[tuple, dict] = {}
        # env_hash → waiters for a worker of that env
        self._worker_waiters: dict[str, collections.deque] = (
            collections.defaultdict(collections.deque)
        )
        self._next_lease = 0
        self._tasks: list[asyncio.Task] = []
        # Worker log capture (reference: workers write to
        # /tmp/ray/session_*/logs and log_monitor.py:116 tails + streams
        # them to drivers). One file per worker on DISK (not shm);
        # _log_monitor_loop tails them into the "logs" pubsub channel.
        from ray_tpu._private import config as _config

        self.log_dir = Path(
            _config.get("LOG_DIR")
            or os.path.join(
                tempfile.gettempdir(),
                f"{os.path.basename(str(store_dir))}-logs",
            )
        )
        self._log_offsets: dict[str, int] = {}  # filename → bytes shipped
        self.spilled_bytes = 0
        self.spilled_objects = 0
        self.oom_kills = 0
        # Read view of this node's object store: the node serves chunked
        # object pulls to other nodes (reference: the raylet's
        # ObjectManager serves Push/Pull, object_manager.h:128) — workers
        # come and go, the node daemon persists.
        self._store_reader = None
        # Peer-node connections for prefetch/broadcast relays, and the
        # location directory for objects anchored here (client-mode puts
        # name this node as owner address).
        self._peers: dict[str, rpc.Connection] = {}
        self._obj_locations: dict[str, set] = {}
        # Resource-view sync state (reference: ray_syncer.h:90 —
        # versioned per-node updates pushed on CHANGE, not polled).
        self._res_version = 0
        self._sync_event: asyncio.Event | None = None
        # DRAINING: set by the head's drain fan-out or by this node's
        # own preemption watcher / SIGTERM handler. A draining node
        # refuses NEW leases (retry_spill bounces the caller to the
        # head, which excludes draining nodes) while existing leases
        # and bundle-backed work keep running until the deadline.
        self.draining = False
        self.drain_info: dict | None = None
        # Per-node dashboard agent (reference: dashboard/agent.py).
        self.agent = None

    # ----------------------------------------------------------- startup
    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        p = await self.server.start(host, port)
        self.addr = f"{host}:{p}"
        from ray_tpu._private import config

        # Reconnecting client: a head restart re-registers this node
        # (the NotifyGCSRestart-equivalent resubscription,
        # reference: node_manager.proto:325).
        self.head = await rpc.ReconnectingClient(
            self.head_addr,
            on_reconnect=self._register_with_head,
            reconnect_timeout=config.get("HEAD_RECONNECT_S"),
        ).connect()
        if config.get("NODE_AGENT"):
            from ray_tpu.runtime.agent import NodeAgent

            self.agent = NodeAgent(self)
            # Loopback by default: the agent serves worker logs over
            # plain HTTP with NO token handshake — binding the node's
            # routable host would leak stdout/stderr to the network.
            # Operators front it with their own proxy/auth via
            # RAY_TPU_NODE_AGENT_HOST.
            await self.agent.start(config.get("NODE_AGENT_HOST"))
        await self._register_with_head(self.head._conn)
        self._sync_event = asyncio.Event()
        self._sync_event.set()  # first wake sends the initial view
        self._tasks.append(asyncio.ensure_future(self._heartbeat_loop()))
        self._tasks.append(asyncio.ensure_future(self._reap_loop()))
        self._tasks.append(asyncio.ensure_future(self._spill_loop()))
        self._tasks.append(asyncio.ensure_future(self._memory_loop()))
        self._tasks.append(asyncio.ensure_future(self._log_monitor_loop()))
        src = self._preemption_source()
        if src is not None:
            self._tasks.append(
                asyncio.ensure_future(self._preemption_watch_loop(src))
            )
        # Prestart workers up to the CPU count so the first task burst
        # doesn't pay Python-interpreter spawn latency per lease
        # (reference: WorkerPool prestarts workers, worker_pool.h:280).
        for _ in range(min(int(self.total.get("CPU", 1)), IDLE_WORKER_CAP)):
            self._spawn_worker()
        return self.addr

    async def stop(self):
        for t in self._tasks:
            t.cancel()
        if self.agent is not None:
            await self.agent.stop()
        for w in self.workers.values():
            proc = w.get("proc")
            if proc and proc.poll() is None:
                proc.terminate()
        for w in self.workers.values():
            proc = w.get("proc")
            if proc:
                try:
                    proc.wait(timeout=2)
                except subprocess.TimeoutExpired:
                    proc.kill()
            core = w.get("core")
            if core is not None:
                # Inproc workers (WORKER_MODE=inproc) have no process
                # to reap: stop their CoreWorker servers/tasks or they
                # keep running on the loop after the node is gone.
                try:
                    await core.stop()
                except Exception:
                    logger.debug(
                        "inproc worker core stop failed during node "
                        "teardown", exc_info=True,
                    )
        if self.head:
            await self.head.close()
        await self.server.stop()

    # ------------------------------------------------------------ workers
    def _spawn_worker(
        self, runtime_env: dict | None = None, ehash: str | None = None
    ) -> str:
        worker_id = WorkerID.random().hex()
        if ehash is None:
            ehash = env_hash(runtime_env)
        from ray_tpu._private import config

        if (runtime_env or {}).get("language") == "cpp":
            # Checked BEFORE the inproc branch: a cpp lease must never
            # silently get a Python CoreWorker (the binary is a real
            # subprocess even in scale-simulation mode).
            return self._spawn_worker_cpp(worker_id, runtime_env, ehash)
        if config.get("WORKER_MODE") == "inproc":
            # Scale-simulation mode (see the WORKER_MODE knob and the
            # reference's many-node release benchmarks,
            # release/benchmarks/distributed/test_many_actors.py): the
            # worker is a CoreWorker on this node's loop. It still
            # dials the node/head over real sockets and registers like
            # a process worker — the control plane cannot tell the
            # difference — but costs ~100 KB instead of an interpreter,
            # so thousands of actors fit one host.
            return self._spawn_worker_inproc(worker_id, runtime_env, ehash)
        # Workers must find the ray_tpu package regardless of their cwd.
        import ray_tpu

        pkg_root = os.path.dirname(os.path.dirname(ray_tpu.__file__))
        pypath = os.environ.get("PYTHONPATH", "")
        if pkg_root not in pypath.split(os.pathsep):
            pypath = f"{pkg_root}{os.pathsep}{pypath}" if pypath else pkg_root
        # Workers inherit the driver's module search path so functions
        # pickled by reference (top-level defs in driver-side modules)
        # import cleanly (reference: ray workers inherit PYTHONPATH/cwd;
        # runtime_env py_modules covers the multi-host case).
        seen = set(pypath.split(os.pathsep))
        for entry in sys.path:
            # exists (not isdir): zipimport archives are valid entries.
            if entry and entry not in seen and os.path.exists(entry):
                pypath = f"{pypath}{os.pathsep}{entry}"
                seen.add(entry)
        jax_platform = env_jax_platform()
        renv = runtime_env or {}
        from ray_tpu.runtime import runtime_env as renv_mod

        in_container = renv_mod.container_image(renv) is not None
        # Pin the env BEFORE reading the build memo: a release-triggered
        # eviction between the two would hand this worker a root whose
        # files are being deleted.
        _env_cache.acquire(ehash)
        built = _built_envs.get(ehash, {})
        python_exe = built.get("python") or sys.executable
        argv = [python_exe, "-m", "ray_tpu.runtime.worker_main"]
        if jax_platform == "cpu" and not built.get("python") and not in_container:
            # CPU workers skip site initialization (the image's
            # sitecustomize imports jax + the TPU plugin, ~1.7 s per
            # interpreter); site-packages comes back via PYTHONPATH.
            # venv workers keep full site init — their pyvenv.cfg is
            # what layers the env's packages over the system's.
            import site

            for sp in site.getsitepackages():
                if sp not in pypath.split(os.pathsep):
                    pypath = f"{pypath}{os.pathsep}{sp}" if pypath else sp
            argv = [sys.executable, "-S", "-m", "ray_tpu.runtime.worker_main"]
        # py_modules: local dirs importable in the worker (single-host or
        # shared-FS; the reference ships them via the runtime_env agent).
        for mod_path in renv.get("py_modules", ()):
            mod_path = os.path.abspath(mod_path)
            if mod_path not in pypath.split(os.pathsep):
                pypath = f"{mod_path}{os.pathsep}{pypath}"
        # Staged working_dir: the worker starts there and imports from it
        # (reference: working_dir runtime env, staged + cwd'd per worker).
        if built.get("cwd"):
            pypath = f"{built['cwd']}{os.pathsep}{pypath}"
        env = {
            **os.environ,
            "PYTHONPATH": pypath,
            **self.worker_env,
            **{str(k): str(v) for k, v in renv.get("env_vars", {}).items()},
            "RAY_TPU_HEAD_ADDR": self.head_addr,
            "RAY_TPU_NODE_ADDR": self.addr or "",
            "RAY_TPU_STORE_DIR": self.store_dir,
            "RAY_TPU_WORKER_ID": worker_id,
            # Workers must not grab the TPU chip the driver holds; they run
            # host code (and JAX CPU) unless a lease says otherwise.
            "JAX_PLATFORMS": jax_platform,
            # Captured stdio is a pipe-to-file, not a tty: without this,
            # worker prints sit in libc buffers and never reach the log
            # pipeline.
            "PYTHONUNBUFFERED": "1",
        }
        try:
            if in_container:
                # Containerized worker (reference: image_uri.py — the
                # worker command runs under podman/docker with host
                # networking and the runtime's paths mounted 1:1 so
                # PYTHONPATH/store paths stay valid inside). Only the
                # vars the worker needs are forwarded — the host
                # environ is not the container's.
                fwd = {
                    k: v
                    for k, v in env.items()
                    if k.startswith(("RAY_TPU_", "PYTHON", "JAX_"))
                    or k in self.worker_env
                    or k in (renv.get("env_vars") or {})
                }
                mounts = [
                    pkg_root,
                    self.store_dir,
                    _ENV_CACHE_ROOT,
                    built.get("cwd") or "",
                    *[
                        os.path.abspath(m)
                        for m in renv.get("py_modules", ())
                    ],
                ]
                argv = renv_mod.wrap_container_argv(
                    renv, argv, fwd, mounts, built.get("cwd")
                )
            # Capture stdio to a per-worker log file (reference: worker
            # logs under /tmp/ray/session_*/logs; log_monitor tails
            # them).
            self.log_dir.mkdir(parents=True, exist_ok=True)
            log_path = self.log_dir / f"worker-{worker_id}.log"
            with open(log_path, "ab") as log_f:
                proc = subprocess.Popen(
                    argv,
                    env=env,
                    cwd=built.get("cwd"),
                    stdout=log_f,
                    stderr=subprocess.STDOUT,
                )
        except Exception:
            # Spawn failed before a worker record existed: nothing will
            # ever release the ref taken above, so release it here or
            # the env is pinned against GC forever.
            _env_cache.release(ehash)
            raise
        self.workers[worker_id] = {
            "proc": proc,
            "state": "spawning",
            "env_hash": ehash,
            "runtime_env": runtime_env,
            "log_path": str(log_path),
        }
        return worker_id

    def _spawn_worker_cpp(
        self, worker_id: str, runtime_env: dict | None, ehash: str
    ) -> str:
        """Spawn the configured C++ worker binary (reference: the C++
        worker the raylet starts for RAY_REMOTE tasks, cpp/src/ray/
        runtime/task/task_executor.cc). It registers back over the
        native wire exactly like a Python worker; the {'language':
        'cpp'} runtime_env gives these their own worker pool, so the
        lease machinery never hands a cpp task to a Python process or
        vice versa."""
        import shlex

        from ray_tpu._private import config

        cmd = config.get("CPP_WORKER_CMD")
        if not cmd:
            raise RuntimeError(
                "runtime_env {'language': 'cpp'} needs RAY_TPU_CPP_"
                "WORKER_CMD to point at a worker binary (build one "
                "with make -C cpp: build/raytpu_worker)"
            )
        _env_cache.acquire(ehash)  # pairs with release on worker death
        env = {
            **os.environ,
            **self.worker_env,
            "RAY_TPU_HEAD_ADDR": self.head_addr,
            "RAY_TPU_NODE_ADDR": self.addr or "",
            "RAY_TPU_STORE_DIR": self.store_dir,
            "RAY_TPU_WORKER_ID": worker_id,
            # The binary reads these from env only (it has no config
            # registry); programmatic overrides would otherwise be
            # invisible to it. Cert/key let it serve AND dial TLS in a
            # --tls cluster.
            "RAY_TPU_AUTH_TOKEN": config.get("AUTH_TOKEN"),
            "RAY_TPU_TLS_CERT": config.get("TLS_CERT"),
            "RAY_TPU_TLS_KEY": config.get("TLS_KEY"),
        }
        try:
            self.log_dir.mkdir(parents=True, exist_ok=True)
            log_path = self.log_dir / f"worker-{worker_id}.log"
            with open(log_path, "ab") as log_f:
                proc = subprocess.Popen(
                    shlex.split(cmd),
                    env=env,
                    stdout=log_f,
                    stderr=subprocess.STDOUT,
                )
        except Exception:
            _env_cache.release(ehash)
            raise
        self.workers[worker_id] = {
            "proc": proc,
            "state": "spawning",
            "env_hash": ehash,
            "runtime_env": runtime_env,
            "log_path": str(log_path),
        }
        return worker_id

    def _spawn_worker_inproc(
        self, worker_id: str, runtime_env: dict | None, ehash: str
    ) -> str:
        # Pair with the unconditional release in the reap loop /
        # _kill_worker: without this, inproc workers decrement a
        # refcount they never took and a registered on-disk env can be
        # evicted while process workers still use it.
        _env_cache.acquire(ehash)
        self.workers[worker_id] = {
            "proc": None,
            "inproc": True,
            "state": "spawning",
            "env_hash": ehash,
            "runtime_env": runtime_env,
            "log_path": "",
        }

        async def boot():
            from ray_tpu.runtime.core_worker import CoreWorker

            core = CoreWorker(
                mode="worker",
                head_addr=self.head_addr,
                node_addr=self.addr or "",
                store_dir=self.store_dir,
                worker_id=worker_id,
            )
            def soft_exit():
                # Mark the record so the reap loop runs the same death
                # path (lease failure, head notification) a subprocess
                # worker's proc.poll() would trigger.
                w2 = self.workers.get(worker_id)
                if w2 is not None:
                    w2["exited"] = True
                asyncio.ensure_future(core.stop())

            core._exit_cb = soft_exit
            try:
                addr = await core.start()
                w = self.workers.get(worker_id)
                if w is None:  # killed while booting
                    await core.stop()
                    return
                w["core"] = core
                await core.node.call(
                    "register_worker",
                    worker_id=worker_id,
                    addr=addr,
                    pid=os.getpid(),
                )
            except Exception:
                logger.warning(
                    "inproc worker %s failed to boot", worker_id,
                    exc_info=True,
                )
                # A subprocess worker dying mid-boot is reaped via
                # proc.poll(); mark this one so the reap loop runs the
                # same path (record cleanup, waiter replacement)
                # instead of leaving a permanent "spawning" zombie
                # whose n_spawning count blocks future spawns.
                w2 = self.workers.get(worker_id)
                if w2 is not None:
                    w2["exited"] = True
                await core.stop()

        asyncio.ensure_future(boot())
        return worker_id

    # ------------------------------------------------------------ leases
    def _feasible(self, resources: dict) -> bool:
        return all(self.total.get(k, 0) >= v for k, v in resources.items())

    def _available(self, resources: dict) -> bool:
        return all(self.available.get(k, 0) >= v for k, v in resources.items())

    def _bump_resources(self):
        """Mark the resource view dirty: the sync loop pushes a
        versioned update to the head as soon as it wakes (reference:
        ray_syncer's per-component version counters — only CHANGED
        state crosses the wire, ray_syncer.h:90)."""
        self._res_version += 1
        if self._sync_event is not None:
            self._sync_event.set()

    def _acquire(self, resources: dict):
        for k, v in resources.items():
            self.available[k] = self.available.get(k, 0) - v
        self._bump_resources()

    def _release(self, resources: dict):
        for k, v in resources.items():
            self.available[k] = self.available.get(k, 0) + v
        self._bump_resources()

    async def _get_worker(self, runtime_env: dict | None = None) -> str:
        """Pop an idle worker of the matching runtime_env, else wait for
        a spawning one; only spawn a fresh process when demand exceeds
        the number already spawning (avoids a thundering herd of Python
        interpreters on cold bursts)."""
        ehash = env_hash(runtime_env)
        bucket = self.idle[ehash]
        if bucket:
            return bucket.pop()
        if runtime_env and (
            runtime_env.get("pip")
            or runtime_env.get("uv")
            or runtime_env.get("conda")
            or runtime_env.get("working_dir")
        ):
            # Build the isolated env (venv + staged working dir) OFF the
            # event loop; cached per env hash, so only the first lease
            # of an env pays (reference: the per-node runtime_env agent
            # builds pip/conda envs with a URI cache,
            # _private/runtime_env/agent/ + uri_cache.py).
            # Thread THIS lease's ehash through build and spawn: the
            # working_dir fingerprint cache has a short TTL, so
            # recomputing at spawn time could hash a just-edited dir
            # differently and miss _built_envs — the worker would then
            # silently start without the env it was leased for.
            await asyncio.get_running_loop().run_in_executor(
                None, build_runtime_env, runtime_env, ehash
            )
        n_spawning = sum(
            1
            for w in self.workers.values()
            if w.get("state") == "spawning" and w.get("env_hash", "") == ehash
        )
        if n_spawning <= len(self._worker_waiters[ehash]):
            self._spawn_worker(runtime_env, ehash=ehash)
        fut = asyncio.get_running_loop().create_future()
        self._worker_waiters[ehash].append(fut)
        return await asyncio.wait_for(fut, SPAWN_TIMEOUT_S)

    async def _grant_lease(
        self, resources: dict, actor: bool, runtime_env: dict | None = None
    ) -> dict:
        self._acquire(resources)
        try:
            worker_id = await self._get_worker(runtime_env)
            w = self.workers[worker_id]
            w["state"] = "leased"
            self._next_lease += 1
            lease_id = f"{self.node_id[:8]}-{self._next_lease}"
            self.leases[lease_id] = Lease(
                lease_id, {**w, "worker_id": worker_id}, resources, actor
            )
            return {
                "ok": True,
                "lease_id": lease_id,
                "worker_id": worker_id,
                "addr": w["addr"],
            }
        except Exception:
            self._release(resources)
            raise

    async def _handle(self, method: str, kw: dict, conn: rpc.Connection):
        fn = getattr(self, f"_on_{method}", None)
        if fn is None:
            raise rpc.RpcError(f"node: unknown method {method!r}")
        return await fn(conn=conn, **rpc.tolerant_kwargs(fn, kw))

    # ------------------------------------------------------- node drain
    async def _on_set_draining(
        self,
        conn,
        draining: bool = True,
        reason: str = "",
        deadline_ts: float | None = None,
    ):
        """Head-pushed drain flag (the head is the authority; this flag
        makes the node's OWN lease path refuse work, which is what
        diverts local-first task/actor placement to other nodes)."""
        was_draining = self.draining
        self.draining = bool(draining)
        self.drain_info = (
            {"reason": reason, "deadline_ts": deadline_ts}
            if draining
            else None
        )
        if draining and not was_draining:
            # Drain-window evacuation, node side: owners push their
            # sole-primary objects to healthy peers; when NO healthy
            # peer exists this store is the last copy of everything in
            # it, so sweep it to the remote tier before retiring.
            asyncio.ensure_future(self._drain_evacuate_store())
        if draining:
            # Queued-but-ungranted leases bounce now — their callers
            # should spill to a node that will outlive them.
            for resources, actor, fut, _ts, _renv in self._pending:
                if not fut.done():
                    fut.set_result(
                        {
                            "ok": False,
                            "retry_spill": True,
                            "draining": True,
                            "error": "node is draining",
                        }
                    )
            self._pending = []
            self._bump_resources()
        return {"ok": True}

    async def _drain_evacuate_store(self) -> None:
        """No-healthy-peer endgame of drain evacuation: push every
        store-resident object to the remote tier (owners cover the
        push-to-peer case; with no peer to push to, the tier is the only
        place the bytes can outlive this node)."""
        from ray_tpu._private import config

        if not config.get("OBJECT_DRAIN_EVACUATION"):
            return
        from ray_tpu.checkpoint import remote as _remote
        from ray_tpu.runtime.drain import EVACUATED

        tier = _remote.get_tier()
        if tier is None or self.head is None:
            return
        try:
            status = await self.head.call("cluster_status")
        except rpc.RpcError:
            return
        draining = set(status.get("draining") or {})
        peers = [
            n
            for nid, n in (status.get("nodes") or {}).items()
            if n.get("addr") and n["addr"] != self.addr
            and nid not in draining
        ]
        if peers:
            return  # owners evacuate to peers; nothing for the tier
        store = self._store()
        for oid in store.iter_ids():
            view = store.get(oid)
            if view is None:
                continue
            try:
                seg_lens = [len(view.inband)] + [
                    len(b) for b in view.buffers
                ]
                payload = bytes(view.inband) + b"".join(
                    bytes(b) for b in view.buffers
                )
                blob = _remote.pack_object(seg_lens, payload)
                await asyncio.to_thread(tier.put_object, oid.hex(), blob)
                EVACUATED.inc(1, tags={"outcome": "remote_tier"})
            except _remote.RemoteTierError as e:
                EVACUATED.inc(1, tags={"outcome": "failed"})
                logger.warning(
                    "drain evacuation of %s to remote tier failed: %s",
                    oid.hex()[:12], e,
                )
            finally:
                store.release(oid)

    async def self_drain(
        self, reason: str, deadline_s: float | None = None
    ) -> None:
        """Self-reported drain (preemption notice, SIGTERM): flip the
        local flag first — no new lease may slip in while the head RPC
        is in flight — then tell the head so the notice fans out."""
        from ray_tpu._private import config

        if deadline_s is None:
            deadline_s = config.get("DRAIN_DEADLINE_S")
        already = self.draining
        self.draining = True
        self.drain_info = {
            "reason": reason,
            "deadline_ts": time.time() + float(deadline_s),
        }
        if already:
            return
        await self._on_set_draining(None, draining=True, reason=reason,
                                    deadline_ts=self.drain_info["deadline_ts"])
        if self.head is not None:
            try:
                await self.head.call(
                    "drain_node",
                    node_id=self.node_id,
                    reason=reason,
                    deadline_s=deadline_s,
                )
            except rpc.RpcError:
                pass

    def _preemption_source(self):
        """Pluggable preemption-notice source: the synthetic
        RAY_TPU_PREEMPT_AFTER_S spec for tests, the GCE maintenance-
        event metadata poller on Google VMs, else none."""
        from ray_tpu._private import config

        spec = config.get("PREEMPT_AFTER_S")
        if spec:
            from ray_tpu._private.test_utils import FakePreemptionSource

            return FakePreemptionSource(spec)
        try:
            with open("/sys/class/dmi/id/product_name") as f:
                on_gce = "Google" in f.read()
        except OSError:
            on_gce = False
        if on_gce:
            try:
                from ray_tpu.autoscaler.gcp import GceMaintenanceEventSource

                return GceMaintenanceEventSource()
            except Exception:
                logger.debug(
                    "GCE maintenance event source unavailable",
                    exc_info=True,
                )
                return None
        return None

    async def _preemption_watch_loop(self, source):
        """Poll the preemption source until it reports a notice, then
        self-drain with the notice's deadline and exit. The poll cadence
        is the source's (metadata endpoints want seconds, the fake wants
        sub-second determinism)."""
        interval = getattr(source, "interval_s", 1.0)
        while not self.draining:
            await asyncio.sleep(interval)
            try:
                notice = source.poll(self)
            except asyncio.CancelledError:
                raise
            # tpulint: allow(broad-except reason=metadata server polled every second; one flaky poll must not kill the watcher and logging each would spam)
            except Exception:
                continue
            if notice is None:
                continue
            reason, deadline_s = notice
            await self.self_drain(reason, deadline_s)
            return

    # ---------------------------------------------------- object serving
    def _store(self):
        if self._store_reader is None:
            from ray_tpu.runtime.object_store import ObjectStore

            self._store_reader = ObjectStore(self.store_dir)
        return self._store_reader

    async def _on_put_object(
        self, conn, oid_hex: str, inband, buffers: list
    ):
        """Store an object pushed by a remote client driver (reference:
        Ray Client server-side put, python/ray/util/client/server/).
        The node's store then serves it to any worker via the normal
        pull protocol."""
        from ray_tpu._private.ids import ObjectID
        from ray_tpu._private.serialization import Serialized

        store = self._store()
        store.put(ObjectID.from_hex(oid_hex), Serialized(inband, list(buffers)))
        return {"ok": True, "holder": self.addr}

    async def _on_put_object_begin(
        self, conn, oid_hex: str, seg_lens: list
    ):
        """Chunked client upload, begin: allocate an assembly buffer."""
        import uuid

        token = uuid.uuid4().hex[:16]
        self._uploads = getattr(self, "_uploads", {})
        self._uploads[token] = {
            "oid_hex": oid_hex,
            "seg_lens": list(seg_lens),
            "buf": bytearray(sum(seg_lens)),
            "ts": time.monotonic(),
        }
        return {"ok": True, "token": token}

    def _prune_uploads(self):
        """Drop abandoned upload buffers (client died mid-stream) —
        called from the reap loop so pruning does not depend on another
        client ever starting an upload."""
        uploads = getattr(self, "_uploads", None)
        if not uploads:
            return
        now = time.monotonic()
        for key in list(uploads):
            if now - uploads[key]["ts"] > 300:
                del uploads[key]

    async def _on_put_object_chunk(
        self, conn, token: str, offset: int, data: bytes
    ):
        up = getattr(self, "_uploads", {}).get(token)
        if up is None:
            return {"ok": False, "error": "unknown upload token"}
        up["buf"][offset : offset + len(data)] = data
        up["ts"] = time.monotonic()
        return {"ok": True}

    async def _on_put_object_commit(self, conn, token: str):
        up = getattr(self, "_uploads", {}).pop(token, None)
        if up is None:
            return {"ok": False, "error": "unknown upload token"}
        from ray_tpu._private.ids import ObjectID
        from ray_tpu._private.serialization import Serialized

        mv = memoryview(bytes(up["buf"]))
        segs = []
        pos = 0
        for n in up["seg_lens"]:
            segs.append(mv[pos : pos + n])
            pos += n
        self._store().put(
            ObjectID.from_hex(up["oid_hex"]),
            Serialized(bytes(segs[0]), [bytes(s) for s in segs[1:]]),
        )
        return {"ok": True, "holder": self.addr}

    async def _on_get_object(self, conn, oid_hex: str):
        """Owner-style lookup served by the node for store-resident
        objects (lets node addresses act as object holders for client
        drivers)."""
        from ray_tpu._private.ids import ObjectID

        if self._store().contains(ObjectID.from_hex(oid_hex)):
            return {
                "kind": "in_store",
                "holder": self.addr,
                "holders": [
                    a
                    for a in self._obj_locations.get(oid_hex, ())
                    if a != self.addr
                ],
            }
        import cloudpickle

        from ray_tpu.exceptions import ObjectLostError

        return {
            "kind": "error",
            "inband": cloudpickle.dumps(
                ObjectLostError(f"object {oid_hex[:12]}… not on this node")
            ),
        }

    async def _on_object_location_add(self, conn, oid_hex: str, addr: str):
        self._obj_locations.setdefault(oid_hex, set()).add(addr)
        return {"ok": True}

    async def _on_object_location_remove(
        self, conn, oid_hex: str, addrs: list
    ):
        locs = self._obj_locations.get(oid_hex)
        if locs:
            locs.difference_update(addrs)
        return {"ok": True}

    async def _connect_peer(
        self, addr: str, retries: int = 3
    ) -> rpc.Connection:
        conn = self._peers.get(addr)
        if conn is not None and not conn._closed:
            return conn
        conn = await rpc.connect(addr, retries=retries)
        self._peers[addr] = conn
        return conn

    async def _on_prefetch_object(
        self, conn, oid_hex: str, owner_addr: str, timeout: float = 120.0
    ):
        """Pull an object into THIS node's store (the broadcast relay
        primitive; reference: push_manager.h:28 — the reference pushes
        chunks at nodes, here the coordinator asks nodes to pull, and
        each completed node registers itself as a source for the next
        wave)."""
        from ray_tpu._private.ids import ObjectID
        from ray_tpu.runtime import transfer
        from ray_tpu._private.serialization import Serialized

        oid = ObjectID.from_hex(oid_hex)
        store = self._store()
        if store.contains(oid):
            return {"ok": True, "cached": True}
        owner = await self._connect_peer(owner_addr)
        # tpulint: allow(rpc-reentrancy reason=owner is a PEER node resolved from owner_addr, never this server; pull_object below would deadlock loopback anyway and never does)
        reply = await owner.call("get_object", oid_hex=oid_hex)
        if reply["kind"] == "value":
            store.put(
                oid, Serialized(reply["inband"], list(reply["buffers"]))
            )
        elif reply["kind"] == "in_store":
            srcs, addr_of = await transfer.connect_sources(
                reply.get("holders"),
                reply.get("holder"),
                self.addr,
                lambda a: self._connect_peer(a, retries=1),
                fallback=owner,
            )
            failed: set = set()
            try:
                inband, buffers = await transfer.pull_object(
                    oid_hex, srcs, timeout, failed=failed
                )
            finally:
                bad = [addr_of[c] for c in failed if c in addr_of]
                if bad:
                    try:
                        # tpulint: allow(rpc-reentrancy reason=owner is a peer node connection, not this process)
                        await owner.call(
                            "object_location_remove",
                            oid_hex=oid_hex,
                            addrs=bad,
                        )
                    except (rpc.ConnectionLost, rpc.RpcError):
                        pass
            store.put(oid, Serialized(inband, list(buffers)))
        else:
            return {"ok": False, "error": f"unexpected kind {reply['kind']}"}
        try:
            # tpulint: allow(rpc-reentrancy reason=owner is a peer node connection, not this process)
            await owner.call(
                "object_location_add", oid_hex=oid_hex, addr=self.addr
            )
        except (rpc.ConnectionLost, rpc.RpcError):
            pass
        return {"ok": True, "cached": False}

    async def _on_prefetch_objects(
        self,
        conn,
        oids: list,
        owner_addr: str,
        timeout: float = 120.0,
        concurrency: int = 4,
    ):
        """Batched prefetch (the checkpoint-replication primitive): pull
        many content-addressed chunks into this node's store from one
        owner, skipping the ones already held. Per-oid results let the
        caller record exactly which replicas landed."""
        sem = asyncio.Semaphore(max(1, concurrency))
        results: dict[str, bool] = {}

        async def one(oid_hex: str):
            async with sem:
                try:
                    r = await self._on_prefetch_object(
                        conn, oid_hex, owner_addr, timeout
                    )
                    results[oid_hex] = bool(r.get("ok"))
                # tpulint: allow(broad-except reason=per-chunk prefetch failure is the RESULT of this batch op, reported per-oid to the caller; logging each would spam on a dead owner)
                except Exception:
                    results[oid_hex] = False

        await asyncio.gather(*(one(o) for o in list(oids)))
        return {"ok": True, "results": results}

    async def _on_delete_objects(self, conn, oids: list):
        """Drop store copies (checkpoint-chunk GC from the head)."""
        from ray_tpu._private.ids import ObjectID

        store = self._store()
        deleted = 0
        for oid_hex in oids:
            try:
                store.delete(ObjectID.from_hex(oid_hex))
                deleted += 1
            except ValueError:
                continue
        return {"ok": True, "deleted": deleted}

    async def _on_ckpt_reconstruct(
        self,
        conn,
        chunk: str,
        k: int,
        m: int,
        member: int,
        rows: list,
        lens: list | None = None,
    ):
        """Erasure repair executor: gather ≥k surviving members of a
        parity group (local store first, then their recorded holders),
        decode the lost member, verify it by content hash, and keep the
        result in THIS node's store. The head picks the node already
        holding the most survivors, so most member reads are local."""
        from ray_tpu.checkpoint import erasure
        from ray_tpu.checkpoint.store import ShardStore, chunk_hash
        from ray_tpu.runtime import transfer

        store = ShardStore(self._store())
        if store.has_chunk(chunk):
            return {"ok": True, "cached": True}
        present: dict[int, bytes] = {}
        for row in rows:
            if len(present) >= int(k):
                break
            mh = row["hash"]
            data = store.get_chunk(mh)
            if data is None:
                for addr in row.get("addrs", ()):
                    if addr == self.addr:
                        continue
                    try:
                        peer = await self._connect_peer(addr, retries=1)
                        data, _bufs = await transfer.pull_object(
                            mh, [peer]
                        )
                    # tpulint: allow(broad-except reason=dead survivor holder mid-repair is expected; the next addr or the next repair tick covers it)
                    except Exception:
                        data = None
                        continue
                    if data is not None and chunk_hash(data) == mh:
                        break
                    data = None
            if data is not None:
                present[int(row["member"])] = data
        if len(present) < int(k):
            return {
                "ok": False,
                "error": f"only {len(present)}/{k} group members "
                "reachable",
            }
        try:
            data = erasure.recover_member(
                int(k), int(m), present, int(member), lens
            )
        # tpulint: allow(broad-except reason=a singular survivor set or corrupt member must report as a typed per-chunk failure to the head, not kill the RPC server)
        except Exception as e:
            return {"ok": False, "error": f"decode failed: {e!r}"}
        if chunk_hash(data) != chunk:
            return {
                "ok": False,
                "error": "reconstructed bytes fail content-hash check",
            }
        store.put_chunk(chunk, data)
        return {"ok": True, "cached": False}

    async def _on_get_object_meta(self, conn, oid_hex: str):
        from ray_tpu._private.ids import ObjectID
        from ray_tpu.runtime.object_store import segment_meta

        oid = ObjectID.from_hex(oid_hex)
        store = self._store()
        view = store.get(oid)
        if view is None:
            return {"ok": False}
        try:
            return segment_meta(view)
        finally:
            # The daemon never exits: cached mmaps would pin shm pages
            # for every object ever served.
            store.release(oid)

    async def _on_get_object_chunk(
        self, conn, oid_hex: str, offset: int, size: int
    ):
        from ray_tpu._private.ids import ObjectID
        from ray_tpu.runtime.object_store import segment_window

        oid = ObjectID.from_hex(oid_hex)
        store = self._store()
        view = store.get(oid)
        if view is None:
            return {"ok": False}
        try:
            return {"ok": True, "data": segment_window(view, offset, size)}
        finally:
            store.release(oid)

    async def _on_register_worker(
        self, conn, worker_id: str, addr: str, pid: int
    ):
        w = self.workers.setdefault(worker_id, {})
        w.update(conn=conn, addr=addr, pid=pid, state="idle")
        conn.state["worker_id"] = worker_id
        self._offer_worker(worker_id)
        return {"ok": True, "node_id": self.node_id}

    def _offer_worker(self, worker_id: str):
        ehash = self.workers.get(worker_id, {}).get("env_hash", "")
        waiters = self._worker_waiters[ehash]
        while waiters:
            fut = waiters.popleft()
            if not fut.done():
                fut.set_result(worker_id)
                return
        self.idle[ehash].append(worker_id)

    async def _on_lease_worker(
        self,
        conn,
        resources: dict | None = None,
        actor: bool = False,
        bundle: tuple | list | None = None,
        runtime_env: dict | None = None,
    ):
        """Grant a worker lease (reference: NodeManager::
        HandleRequestWorkerLease node_manager.h:290). Infeasible requests
        fail fast; unavailable ones queue until resources free up. With
        ``bundle`` = (pg_id, index), resources come from that reserved
        placement-group bundle instead of the node's general pool."""
        resources = dict(resources or {"CPU": 1.0})
        if self.draining and bundle is None:
            # retry_spill (not infeasible): the caller's spillback path
            # re-picks through the head, which excludes draining nodes.
            # Bundle-backed leases stay honored — the bundle was gang-
            # reserved before the drain and dies with the node anyway.
            return {
                "ok": False,
                "retry_spill": True,
                "draining": True,
                "error": "node is draining; lease elsewhere",
            }
        if bundle is not None:
            b = self.bundles.get(tuple(bundle))
            if b is None:
                return {"ok": False, "error": f"no bundle {bundle} here"}
            if any(b["available"].get(k, 0) < v for k, v in resources.items()):
                return {
                    "ok": False,
                    "error": f"bundle {bundle} lacks {resources}",
                }
            for k, v in resources.items():
                b["available"][k] -= v
            # The lease draws on the bundle, not the general pool — spawn
            # a worker without double-charging node resources. Credit the
            # bundle back if the grant itself fails (worker spawn error).
            try:
                grant = await self._grant_lease({}, actor, runtime_env)
            except Exception:
                for k, v in resources.items():
                    b["available"][k] += v
                raise
            lease = self.leases[grant["lease_id"]]
            lease.bundle = tuple(bundle)
            lease.bundle_resources = resources
            grant["bundle"] = tuple(bundle)
            return grant
        if not self._feasible(resources):
            return {
                "ok": False,
                "infeasible": True,
                "error": f"infeasible request {resources} on {self.total}",
            }
        if self._available(resources):
            return await self._grant_lease(resources, actor, runtime_env)
        fut = asyncio.get_running_loop().create_future()
        self._pending.append(
            (resources, actor, fut, asyncio.get_running_loop().time(),
             runtime_env)
        )
        self._bump_resources()  # queued demand is a scale-up signal
        return await fut

    def _credit_bundle(self, lease: "Lease"):
        if lease.bundle is None:
            return
        b = self.bundles.get(lease.bundle)
        if b is not None and lease.bundle_resources:
            for k, v in lease.bundle_resources.items():
                b["available"][k] = b["available"].get(k, 0) + v

    async def _on_return_lease(self, conn, lease_id: str):
        lease = self.leases.pop(lease_id, None)
        if lease is None:
            return {"ok": False}
        self._release(lease.resources)
        self._credit_bundle(lease)
        worker_id = lease.worker["worker_id"]
        w = self.workers.get(worker_id)
        if w and w.get("state") == "leased":
            w["state"] = "idle"
            ehash = w.get("env_hash", "")
            if self._worker_waiters[ehash]:
                # Hand the warm worker straight to a blocked lease grant
                # rather than parking (or killing) it while the grant
                # waits out an interpreter spawn.
                self._offer_worker(worker_id)
            else:
                self.idle[ehash].append(worker_id)
                self._enforce_idle_cap()
        self._drain_pending()
        return {"ok": True}

    async def _on_reserve_bundle(
        self, conn, pg_id: str, index: int, resources: dict
    ):
        resources = dict(resources)
        if self.draining and (pg_id, index) not in self.bundles:
            # The head's planner already excludes draining nodes; this
            # backstops a plan computed before the drain landed.
            return {
                "ok": False,
                "error": f"node {self.node_id[:8]} is draining",
            }
        if (pg_id, index) in self.bundles:
            # Idempotent re-reserve: the head may retry after a lost
            # response (reference: node_manager.proto documents per-RPC
            # idempotence for the 2PC prepare/commit).
            return {"ok": True}
        if not self._available(resources):
            return {
                "ok": False,
                "error": f"bundle {resources} unavailable on {self.node_id[:8]}",
            }
        self._acquire(resources)
        self.bundles[(pg_id, index)] = {
            "total": resources,
            "available": dict(resources),
        }
        return {"ok": True}

    async def _on_free_bundle(self, conn, pg_id: str, index: int):
        b = self.bundles.pop((pg_id, index), None)
        if b is None:
            return {"ok": False}
        self._release(b["total"])
        self._drain_pending()
        return {"ok": True}

    async def _on_kill_worker(self, conn, worker_id: str, force: bool = True):
        self._kill_worker(worker_id)
        self._release_worker_leases(worker_id)
        # _kill_worker drops the record, so the reap loop never sees this
        # death — publish it here or collective groups (and any other
        # "worker" subscriber) would only learn via op deadlines.
        if self.head:
            try:
                await self.head.call(
                    "publish",
                    channel="worker",
                    msg={"event": "died", "worker_id": worker_id},
                )
            except rpc.RpcError:
                pass
        return {"ok": True}

    def _release_worker_leases(self, worker_id: str):
        """Free leases of a worker killed OUTSIDE the reap loop
        (_kill_worker removes it from the table so the reap loop never
        sees the death, and lease holders that saw ConnectionLost will
        not return their lease)."""
        for lease_id, lease in list(self.leases.items()):
            if lease.worker["worker_id"] == worker_id:
                self.leases.pop(lease_id)
                self._release(lease.resources)
                self._credit_bundle(lease)
        self._drain_pending()

    async def _on_list_workers(self, conn):
        """Worker inventory for chaos tooling and debugging (reference:
        the state API's worker table; killers in test_utils.py:1646)."""
        out = []
        leased_ids = {
            lease.worker["worker_id"]: lease.actor
            for lease in self.leases.values()
        }
        for wid, w in self.workers.items():
            out.append({
                "worker_id": wid,
                "pid": w.get("pid"),
                "state": w.get("state"),
                "leased": wid in leased_ids,
                "is_actor": bool(leased_ids.get(wid)),
            })
        return {"workers": out}

    async def _on_node_info(self, conn):
        return {
            "node_id": self.node_id,
            "addr": self.addr,
            "resources": self.total,
            "available": self.available,
            "n_workers": len(self.workers),
            "store_dir": self.store_dir,
            "spilled_bytes": self.spilled_bytes,
            "spilled_objects": self.spilled_objects,
            "oom_kills": self.oom_kills,
            "draining": self.draining,
            "drain_info": self.drain_info,
        }

    def _enforce_idle_cap(self):
        """Cap TOTAL idle workers across all runtime_env pools: many
        distinct envs must not each park IDLE_WORKER_CAP interpreters.
        Evicts from the fullest bucket (oldest entry first)."""
        while (
            sum(len(b) for b in self.idle.values()) > IDLE_WORKER_CAP
        ):
            ehash = max(self.idle, key=lambda k: len(self.idle[k]))
            victim = self.idle[ehash].pop(0)
            self._kill_worker(victim)

    def _kill_worker(self, worker_id: str):
        w = self.workers.pop(worker_id, None)
        if not w:
            return
        ehash = w.get("env_hash", "")
        if worker_id in self.idle[ehash]:
            self.idle[ehash].remove(worker_id)
        proc = w.get("proc")
        if proc and proc.poll() is None:
            proc.kill()
        core = w.get("core")
        if core is not None:  # inproc worker: stop its rpc endpoints
            asyncio.ensure_future(core.stop())
        _env_cache.release(ehash)

    def _drain_pending(self):
        now = asyncio.get_event_loop().time()
        still = []
        for resources, actor, fut, ts, runtime_env in self._pending:
            if fut.done():
                continue
            if self._available(resources):
                asyncio.ensure_future(
                    self._fulfil(resources, actor, fut, runtime_env)
                )
            elif now - ts > PENDING_SPILL_S:
                fut.set_result(
                    {"ok": False, "retry_spill": True,
                     "error": "queued past age limit; spill via head"}
                )
            else:
                still.append((resources, actor, fut, ts, runtime_env))
        if len(still) != len(self._pending):
            self._bump_resources()
        self._pending = still

    async def _fulfil(self, resources, actor, fut, runtime_env=None):
        try:
            result = await self._grant_lease(resources, actor, runtime_env)
            if not fut.done():
                fut.set_result(result)
        # tpulint: allow(broad-except reason=failure propagates to the waiter via fut.set_exception, not swallowed)
        except Exception as e:
            if not fut.done():
                fut.set_exception(e)

    # ------------------------------------------------------------- loops
    async def _log_monitor_loop(self):
        """Tail worker log files and publish new output on the "logs"
        pubsub channel; drivers subscribed there print it (reference:
        LogMonitor log_monitor.py:116 tails /tmp/ray/session_*/logs and
        streams to the driver, worker.py:2295 print_worker_logs)."""
        MAX_SHIP = 64 * 1024  # per worker per tick; floods are chunked
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(0.3)
            try:
                if self.head is None or not self.log_dir.is_dir():
                    continue
                for path in self.log_dir.glob("worker-*.log"):
                    name = path.name
                    try:
                        size = path.stat().st_size
                    except OSError:
                        continue
                    off = self._log_offsets.get(name, 0)
                    if size <= off:
                        continue

                    def read_chunk(path=path, off=off):
                        with open(path, "rb") as f:
                            f.seek(off)
                            return f.read(MAX_SHIP)

                    data = await loop.run_in_executor(None, read_chunk)
                    if not data:
                        continue
                    wid = name[len("worker-"):-len(".log")]
                    w = self.workers.get(wid, {})
                    # retry=False: a publish whose ack was lost across a
                    # head restart must not re-send — subscribers would
                    # see the same log chunk twice. The offset advances
                    # only once the chunk was (at least) handed to the
                    # wire: a provably-unsent chunk (sent=False) is
                    # re-read next tick instead of vanishing.
                    try:
                        await self.head.call(
                            "publish",
                            retry=False,
                            channel="logs",
                            msg={
                                "worker_id": wid,
                                "node_id": self.node_id,
                                "pid": w.get("pid"),
                                "data": data.decode("utf-8", "replace"),
                            },
                        )
                    except rpc.RpcError as e:
                        if getattr(e, "sent", True) is False:
                            continue  # never reached the wire: retry it
                    self._log_offsets[name] = off + len(data)
            except asyncio.CancelledError:
                raise
            except Exception:
                # Best-effort: the node's own logger is NOT among the
                # tailed worker logs, so this cannot feedback-loop.
                logger.debug("log shipping tick failed", exc_info=True)

    async def _on_list_logs(self, conn):
        out = []
        if self.log_dir.is_dir():
            for path in sorted(self.log_dir.glob("worker-*.log")):
                wid = path.name[len("worker-"):-len(".log")]
                w = self.workers.get(wid)
                try:
                    size = path.stat().st_size
                except OSError:
                    continue
                out.append(
                    {
                        "worker_id": wid,
                        "size": size,
                        "alive": bool(
                            w
                            and w.get("proc")
                            and w["proc"].poll() is None
                        ),
                    }
                )
        return {"logs": out, "node_id": self.node_id}

    async def _on_read_log(
        self,
        conn,
        worker_id: str,
        offset: int = 0,
        max_bytes: int = 1 << 20,
    ):
        """Serve a worker's captured log — including DEAD workers'
        (reference: `ray logs` reads session log files after the worker
        exits). Prefix match on worker_id; negative offset = tail."""
        matches = [
            p
            for p in self.log_dir.glob("worker-*.log")
            if p.name[len("worker-"):-len(".log")].startswith(worker_id)
        ]
        if not matches:
            return {"ok": False, "error": f"no log for worker {worker_id!r}"}
        path = sorted(matches)[0]
        size = path.stat().st_size
        if offset < 0:
            offset = max(0, size + offset)
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(max_bytes)
        return {
            "ok": True,
            "worker_id": path.name[len("worker-"):-len(".log")],
            "offset": offset,
            "size": size,
            "data": data,
        }

    async def _register_with_head(self, conn: "rpc.Connection"):
        """(Re-)announce this node. Runs at startup AND after every head
        reconnect, so a restarted head rebuilds its node table from live
        nodes (reference: raylet re-registration on NotifyGCSRestart)."""
        await conn.call(
            "register_node",
            node_id=self.node_id,
            addr=self.addr,
            resources=self.total,
            # The CURRENT view, not the totals: re-registration after a
            # connection blip must not reset the head to full capacity
            # while leases are live.
            available=self.available,
            res_version=self._res_version,
            labels=self.labels,
            agent_addr=self.agent.addr if self.agent else None,
        )
        # Force a follow-up sync regardless: the version counter keeps
        # moving, so a concurrent change between snapshot and reply
        # can't be skipped as already-sent.
        self._bump_resources()

    _SYNC_KEEPALIVE_S = 5.0
    _SYNC_DEBOUNCE_S = 0.02

    async def _heartbeat_loop(self):
        """Resource-view sync (reference: ray_syncer.h:90 — streaming
        versioned updates, not polling). A resource CHANGE (lease
        grant/release, queued demand, bundle ops) wakes this loop
        immediately and pushes one versioned update — sub-50ms
        propagation instead of a 2s poll; an unchanged view sends only
        a tiny keepalive every _SYNC_KEEPALIVE_S so the head's health
        loop still sees liveness. At 2,000 idle nodes this is ~400
        payload-free messages/s cluster-wide instead of 1,000 full
        snapshots/s."""
        sent_version = -1
        while True:
            try:
                await asyncio.wait_for(
                    self._sync_event.wait(), timeout=self._SYNC_KEEPALIVE_S
                )
                # Coalesce bursts (a lease storm is one update).
                await asyncio.sleep(self._SYNC_DEBOUNCE_S)
            except asyncio.TimeoutError:
                pass
            self._sync_event.clear()
            version = self._res_version
            try:
                if version != sent_version:
                    reply = await self.head.call(
                        "sync",
                        node_id=self.node_id,
                        version=version,
                        available=self.available,
                        # Feasible-but-queued lease demand: a scale-up
                        # signal (reference: raylets report
                        # resource_load_by_shape to GCS for
                        # GcsAutoscalerStateManager).
                        pending=[dict(r) for r, *_rest in self._pending],
                    )
                    if reply.get("ok"):
                        sent_version = version
                else:
                    reply = await self.head.call(
                        "keepalive", node_id=self.node_id
                    )
                if not reply.get("ok") and reply.get("reregister"):
                    # The head lost this node's entry (restart, or a
                    # health-loop reap during a long GC pause): rejoin
                    # and force a full re-send.
                    await self._register_with_head(self.head._conn)
                    sent_version = -1
                    self._sync_event.set()
            except rpc.RpcError:
                pass

    async def _spill_loop(self):
        """Watermark-driven object spilling: when the node's shm store
        runs past SPILL_HIGH of capacity, move the coldest sealed
        objects to disk until usage drops below SPILL_LOW. Spilled
        objects are served transparently by ObjectStore.get (and the
        pull protocol), so readers never notice."""
        while True:
            await asyncio.sleep(0.5)
            try:
                high, low = _spill_watermarks()
                store = self._store()
                cap = store.capacity_bytes
                if not cap:
                    continue

                def spill_tick():
                    # All filesystem scanning runs here, OFF the event
                    # loop: the daemon also serves chunked object pulls
                    # and must not stall on iterdir/stat storms.
                    used = store.used_bytes()
                    if used <= high * cap:
                        return 0, 0
                    target = low * cap
                    freed_total = 0
                    n = 0
                    for oid, _size, _lru in store.spill_candidates():
                        if used - freed_total <= target:
                            break
                        try:
                            freed = store.spill_one(oid)
                        except OSError:
                            continue
                        if freed:
                            freed_total += freed
                            n += 1
                    return freed_total, n

                freed, n = await asyncio.to_thread(spill_tick)
                self.spilled_bytes += freed
                self.spilled_objects += n
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.warning(
                    "object spill tick failed (disk full? bad spill "
                    "dir?)", exc_info=True,
                )

    async def _memory_loop(self):
        """Kill a worker when the host runs out of memory (reference:
        MemoryMonitor memory_monitor.h:52 + WorkerKillingPolicy
        worker_killing_policy.h:33). Policy: newest NON-ACTOR lease
        first — its task is retriable and has lost the least work;
        actors are last resorts (their state dies with them)."""
        from ray_tpu._private import config

        while True:
            await asyncio.sleep(1.0)
            try:
                # Re-read each tick so runtime overrides apply, same as
                # the spill watermarks.
                if system_memory_fraction() < config.get("MEMORY_THRESHOLD"):
                    continue
                victim = self._pick_oom_victim()
                if victim is None:
                    continue
                lease, wid = victim
                self.oom_kills += 1
                rss = worker_rss_bytes(lease.worker.get("pid") or 0)
                self._kill_worker(wid)
                self._release_worker_leases(wid)
                if self.head:
                    try:
                        await self.head.call(
                            "publish",
                            channel="worker",
                            msg={
                                "event": "oom_killed",
                                "worker_id": wid,
                                "node_id": self.node_id,
                                "rss": rss,
                            },
                        )
                    except rpc.RpcError:
                        pass
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.debug("memory monitor tick failed",
                             exc_info=True)

    def _pick_oom_victim(self):
        """(lease, worker_id) to kill, or None. Newest task lease first,
        then newest actor lease (reference: the retriable-first ordering
        of worker_killing_policy_group_by_owner.h:87)."""
        candidates = sorted(
            (
                (not lease.actor, lease.granted_at, lease, lease.worker["worker_id"])
                for lease in self.leases.values()
                if lease.worker.get("worker_id") in self.workers
            ),
            key=lambda t: (t[0], t[1]),
            reverse=True,
        )
        if not candidates:
            return None
        _, _, lease, wid = candidates[0]
        return lease, wid

    async def _reap_loop(self):
        """Detect worker process death and fail affected leases
        (reference: raylet detects worker death via process wait + IPC
        disconnect, SURVEY.md section 5)."""
        while True:
            await asyncio.sleep(1.0)
            # Age-bounce stale queued leases even when no grant/return
            # event fires (the age check lives in _drain_pending).
            self._drain_pending()
            self._prune_uploads()
            dead = [
                wid
                for wid, w in self.workers.items()
                if (
                    w.get("proc") is not None
                    and w["proc"].poll() is not None
                )
                or w.get("exited")  # inproc worker told to exit
            ]
            for wid in dead:
                w = self.workers.pop(wid, None)
                ehash = (w or {}).get("env_hash", "")
                if wid in self.idle[ehash]:
                    self.idle[ehash].remove(wid)
                _env_cache.release(ehash)
                if (
                    w
                    and w.get("state") == "spawning"
                    and self._worker_waiters[ehash]
                ):
                    # A worker died mid-spawn with grants still blocked on
                    # registration — spawn a replacement (same runtime_env)
                    # rather than letting the waiter run out the timeout.
                    # Reuse the dead worker's ehash: recomputing could
                    # hash an edited working_dir differently and strand
                    # the waiters in the old bucket.
                    self._spawn_worker(w.get("runtime_env"), ehash=ehash)
                for lease_id, lease in list(self.leases.items()):
                    if lease.worker["worker_id"] == wid:
                        self.leases.pop(lease_id)
                        self._release(lease.resources)
                        self._credit_bundle(lease)
                if self.head:
                    try:
                        await self.head.call(
                            "publish",
                            channel="worker",
                            msg={"event": "died", "worker_id": wid},
                        )
                    except rpc.RpcError:
                        pass
            if dead:
                self._drain_pending()


def detect_labels() -> dict[str, str]:
    """Node labels: accelerator topology from the plugin registry
    (reference: TPU env vars become labels, accelerators/tpu.py:18–66 +
    util/tpu.py slice labels) plus user labels from RAY_TPU_NODE_LABELS
    (k=v,k=v)."""
    from ray_tpu._private import config
    from ray_tpu._private.accelerators import detect_accelerator_labels

    labels: dict[str, str] = {}
    for pair in config.get("NODE_LABELS").split(","):
        if "=" in pair:
            k, v = pair.split("=", 1)
            labels[k.strip()] = v.strip()
    labels.update(detect_accelerator_labels())
    labels.update(_gce_metadata_labels())
    # Canonical slice fault-domain label: the head's slice table, the
    # checkpoint replicator's cross-slice placement, and the autoscaler's
    # slice-unit replacement all key on "slice". On real TPU VMs the
    # accelerator plugin reports the slice name under the ray-style
    # label; alias it unless the operator set "slice" explicitly.
    if "slice" not in labels and labels.get("ray_tpu.io/tpu-slice-name"):
        labels["slice"] = labels["ray_tpu.io/tpu-slice-name"]
    return labels


def _gce_metadata_labels() -> dict[str, str]:
    """On GCE/GKE VMs, pick up the provider id the autoscaler stamped
    into instance metadata (gcp.py create_node) so the autoscaler can
    map its provider node ids to registered runtime nodes. The DMI
    product name gates the network probe — non-GCE hosts never touch
    the metadata endpoint."""
    try:
        with open("/sys/class/dmi/id/product_name") as f:
            if "Google" not in f.read():
                return {}
    except OSError:
        return {}
    import urllib.request

    labels: dict[str, str] = {}
    base = "http://metadata.google.internal/computeMetadata/v1/instance/"
    # node_pool-mode slices have no stamped provider id (setSize is
    # anonymous); the instance NAME is what the provider's targeted
    # scale-down and runtime_node_id match against instead.
    for path, key in (
        ("attributes/ray-tpu-provider-id", "ray-tpu-provider-id"),
        ("name", "ray-tpu-gce-instance"),
    ):
        try:
            req = urllib.request.Request(
                base + path, headers={"Metadata-Flavor": "Google"}
            )
            with urllib.request.urlopen(req, timeout=2) as resp:
                value = resp.read().decode().strip()
            if value:
                labels[key] = value
        except OSError:
            pass
    return labels


def env_jax_platform() -> str:
    # Worker processes default to CPU JAX; TPU-holding workers are
    # configured explicitly by the trainer/collective layer.
    from ray_tpu._private import config

    return config.get("WORKER_JAX_PLATFORMS")
