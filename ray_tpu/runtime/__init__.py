"""Process-level runtime: head service (cluster metadata + scheduling),
node manager (worker pool + leases + shared-memory store), core worker
(ownership, task submission/execution). See SURVEY.md sections 1-3 for the
reference architecture this mirrors (GCS / raylet / core_worker)."""
