"""Runtime environments beyond pip/uv: conda envs, containerized
workers, and the refcounted URI cache that garbage-collects
unreferenced builds.

Reference: python/ray/_private/runtime_env/conda.py (named env vs
yaml/dict spec → created env, worker python swapped), image_uri.py
(podman run of the worker command with the session mounted), and
uri_cache.py (size-capped cache, in-use URIs pinned, LRU eviction of
unreferenced entries) — the per-node runtime_env agent glues those
together; here the NodeManager plays that role directly.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import threading
import time


# ----------------------------------------------------------------- conda


def _conda_bin() -> str:
    conda = shutil.which("conda")
    if conda is None:
        raise RuntimeError(
            "runtime_env requested a conda env but no `conda` binary is "
            "on PATH of this node"
        )
    return conda


def build_conda_env(spec, root: str) -> str:
    """Materialize a ``conda:`` runtime env; returns the env's python.

    Accepted spec shapes (reference: conda.py get_conda_dict):
    - ``"envname"`` — a pre-existing named env; nothing is built.
    - ``"path/to/environment.yml"`` — created from that file.
    - ``{"dependencies": [...], ...}`` — env dict, written out and built.
    - ``["numpy", ...]`` — shorthand for ``{"dependencies": [...]}``.

    Built envs live at ``<root>/conda`` with a ``.ready`` marker, so a
    crash mid-build rebuilds from scratch (same protocol as the venv
    builder in node.py).
    """
    conda = _conda_bin()
    if isinstance(spec, str) and not spec.endswith((".yml", ".yaml")):
        # Pre-existing named env: resolve its interpreter through conda
        # itself (the env may live in any configured envs_dir).
        proc = subprocess.run(
            [conda, "run", "-n", spec, "python", "-c",
             "import sys; print(sys.executable)"],
            capture_output=True,
            text=True,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"runtime_env conda env {spec!r} is not usable:\n"
                f"{proc.stderr[-2000:]}"
            )
        return proc.stdout.strip().splitlines()[-1]

    prefix = os.path.join(root, "conda")
    marker = os.path.join(prefix, ".ready")
    python = os.path.join(prefix, "bin", "python")
    if os.path.exists(marker):
        return python
    os.makedirs(root, exist_ok=True)
    shutil.rmtree(prefix, ignore_errors=True)
    if isinstance(spec, str):
        env_file = spec
    else:
        if isinstance(spec, (list, tuple)):
            spec = {"dependencies": list(spec)}
        env_file = os.path.join(root, "environment.yml")
        with open(env_file, "w") as f:
            # JSON is a YAML subset — no yaml dependency needed.
            json.dump(spec, f)
    proc = subprocess.run(
        [conda, "env", "create", "--prefix", prefix, "--file", env_file],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"runtime_env conda env create failed:\n{proc.stderr[-2000:]}"
        )
    if not os.path.exists(python):
        raise RuntimeError(
            f"conda env created at {prefix} but {python} does not exist"
        )
    with open(marker, "w") as f:
        f.write("ok")
    return python


# ------------------------------------------------------------- container


def container_engine() -> str | None:
    for engine in ("podman", "docker"):
        path = shutil.which(engine)
        if path:
            return path
    return None


def container_image(renv: dict) -> str | None:
    """The image a runtime_env requests, or None. Both reference
    shapes: ``image_uri: "img"`` and ``container: {"image": "img"}``."""
    spec = renv.get("container")
    if isinstance(spec, dict) and spec.get("image"):
        return spec["image"]
    return renv.get("image_uri")


def wrap_container_argv(
    renv: dict,
    argv: list[str],
    env: dict[str, str],
    mounts: list[str],
    workdir: str | None,
) -> list[str]:
    """Rewrite a worker command to run inside the requested image
    (reference: image_uri.py _modify_context — podman run with the
    session dir mounted and the worker env forwarded).

    ``--network host`` because the worker dials the head/node over
    loopback TCP; every mount is host-path == container-path so the
    PYTHONPATH and store paths the runtime computed stay valid inside.
    """
    engine = container_engine()
    if engine is None:
        raise RuntimeError(
            "runtime_env requested a container image but neither "
            "podman nor docker is on PATH of this node"
        )
    image = container_image(renv)
    spec = renv.get("container") or {}
    # The worker must run the IMAGE's interpreter: the host
    # sys.executable path does not exist inside (and is deliberately
    # not mounted — the image owns its python and site-packages).
    argv = [spec.get("worker_python", "python3"), *argv[1:]]
    cmd = [engine, "run", "--rm", "--network", "host"]
    seen: set[str] = set()
    for m in mounts:
        if m and m not in seen and os.path.exists(m):
            seen.add(m)
            cmd += ["-v", f"{m}:{m}"]
    for k, v in env.items():
        cmd += ["--env", f"{k}={v}"]
    if workdir:
        cmd += ["--workdir", workdir]
    cmd += list(spec.get("run_options", ()))
    cmd.append(image)
    cmd += argv
    return cmd


# --------------------------------------------------------------- GC cache


def _tree_bytes(root: str) -> int:
    total = 0
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in filenames:
            try:
                total += os.path.getsize(os.path.join(dirpath, fn))
            except OSError:
                pass
    return total


def _foreign_live_refs(root: str) -> bool:
    """True if ANOTHER live process holds a pid-marker ref on this env
    root. Several node daemons can share one host cache
    (build_runtime_env's per-hash flock exists for exactly that), so
    this process's refcounts alone must never justify deleting the
    tree another daemon's workers run from."""
    refs_dir = os.path.join(root, ".refs")
    try:
        marks = os.listdir(refs_dir)
    except OSError:
        return False
    me = str(os.getpid())
    for mark in marks:
        if mark == me or not mark.isdigit():
            continue
        try:
            os.kill(int(mark), 0)
            return True  # foreign pid alive → pinned
        except ProcessLookupError:
            # Stale marker from a dead daemon: clean as we go.
            try:
                os.unlink(os.path.join(refs_dir, mark))
            except OSError:
                pass
        except PermissionError:
            return True  # alive under another uid
    return False


class UriCache:
    """Refcounted, byte-capped registry of built env roots (reference:
    uri_cache.py URICache — in-use URIs are pinned; once total size
    exceeds the cap, unreferenced entries evict oldest-idle-first).

    The NodeManager acquires an env when a worker spawns into it and
    releases on worker death; eviction forgets the entry (``on_evict``
    drops the build memo so nothing hands out the dying root), then
    deletes the tree on a background thread — a multi-GB conda env
    rmtree must not stall the node's event loop.

    Three guards against deleting an env someone still needs:
    - local refcounts (this daemon's live workers),
    - a per-root ``.refs/<pid>`` marker checked across processes
      (sibling daemons sharing the host cache),
    - ``min_idle_s``: an entry is only evictable after sitting
      unreferenced for a grace period, closing the build→spawn window
      where a fresh env has no ref yet.
    """

    def __init__(self, max_total_bytes: int, on_evict=None,
                 min_idle_s: float = 30.0, delete_fn=None):
        self.max_total_bytes = max_total_bytes
        self.min_idle_s = min_idle_s
        self._on_evict = on_evict
        # delete_fn(h, root) runs on the GC thread; the node passes one
        # that holds the per-hash build flock so a concurrent rebuild of
        # the same hash cannot interleave with the delete.
        self._delete_fn = delete_fn or (
            lambda _h, root: shutil.rmtree(root, ignore_errors=True)
        )
        self._lock = threading.Lock()
        # hash → {root, bytes, refs, last_used}
        self._entries: dict[str, dict] = {}

    def _pid_mark(self, root: str) -> str:
        return os.path.join(root, ".refs", str(os.getpid()))

    def register(self, h: str, root: str):
        if not h:
            return
        with self._lock:
            entry = self._entries.get(h)
            if entry is None or entry["root"] != root:
                self._entries[h] = {
                    "root": root,
                    "bytes": _tree_bytes(root),
                    "refs": 0,
                    "last_used": time.monotonic(),
                }

    def acquire(self, h: str):
        if not h:
            return
        with self._lock:
            entry = self._entries.get(h)
            if entry is None:
                return
            entry["refs"] += 1
            entry["last_used"] = time.monotonic()
            mark = self._pid_mark(entry["root"])
        try:
            os.makedirs(os.path.dirname(mark), exist_ok=True)
            with open(mark, "w"):
                pass
        except OSError:
            pass

    def release(self, h: str):
        if not h:
            return
        evicted: list[tuple[str, str]] = []
        with self._lock:
            entry = self._entries.get(h)
            if entry is not None:
                entry["refs"] = max(0, entry["refs"] - 1)
                entry["last_used"] = time.monotonic()
                if entry["refs"] == 0:
                    try:
                        os.unlink(self._pid_mark(entry["root"]))
                    except OSError:
                        pass
            evicted = self._evict_locked()
        for eh, _root in evicted:
            if self._on_evict:
                self._on_evict(eh)
        if evicted:
            threading.Thread(
                target=lambda: [
                    self._delete_fn(h, root) for h, root in evicted
                ],
                name="ray_tpu-env-gc",
                daemon=True,
            ).start()

    def total_bytes(self) -> int:
        with self._lock:
            return sum(e["bytes"] for e in self._entries.values())

    def refs(self, h: str) -> int:
        with self._lock:
            entry = self._entries.get(h)
            return entry["refs"] if entry else 0

    def _evict_locked(self) -> list[tuple[str, str]]:
        evicted = []
        now = time.monotonic()
        total = sum(e["bytes"] for e in self._entries.values())
        idle = sorted(
            (
                h
                for h, e in self._entries.items()
                if e["refs"] == 0 and now - e["last_used"] >= self.min_idle_s
            ),
            key=lambda h: self._entries[h]["last_used"],
        )
        for h in idle:
            if total <= self.max_total_bytes:
                break
            if _foreign_live_refs(self._entries[h]["root"]):
                continue
            entry = self._entries.pop(h)
            total -= entry["bytes"]
            evicted.append((h, entry["root"]))
        return evicted
