"""Pluggable head-state persistence (GCS fault tolerance).

The reference backs its GCS tables with a storage abstraction —
in-memory or Redis (reference: gcs/store_client/redis_store_client.h:126,
gcs_table_storage.h:200) — so a head restart reloads cluster metadata
and nodes resubscribe (node_manager.proto:325 NotifyGCSRestart). The
TPU-native equivalent here is an append-only local journal: every
durable mutation (KV, actor registry, placement groups) appends one
pickled record; restart replays the journal and then compacts it to a
single snapshot record. No external service required — the journal file
on shared storage is the single-host analogue; the same interface admits
a Redis-protocol backend later.

Record format: length-prefixed pickle frames, `(table, op, payload)`.
A truncated tail (crash mid-append) is ignored on replay.
"""

from __future__ import annotations

import os
import pickle
import struct
import tempfile
from typing import Any, Iterator

_HDR = struct.Struct("<I")


class FileJournal:
    """Append-only journal with replay + snapshot compaction."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = None

    # ------------------------------------------------------------ write
    def append(self, record: tuple) -> None:
        if self._f is None:
            self._f = open(self.path, "ab")
        data = pickle.dumps(record, protocol=5)
        self._f.write(_HDR.pack(len(data)) + data)
        self._f.flush()

    # ------------------------------------------------------------- read
    def replay(self) -> Iterator[tuple]:
        """All intact records, oldest first; stops at a torn tail."""
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            while True:
                hdr = f.read(_HDR.size)
                if len(hdr) < _HDR.size:
                    return
                (length,) = _HDR.unpack(hdr)
                data = f.read(length)
                if len(data) < length:
                    return  # torn append from a crash — discard
                try:
                    yield pickle.loads(data)
                except Exception:  # noqa: BLE001 - corrupt frame ends replay
                    return

    def compact(self, snapshot: Any) -> None:
        """Atomically replace the journal with one snapshot record."""
        self.close()
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(self.path) or ".", prefix=".journal-"
        )
        try:
            with os.fdopen(fd, "wb") as f:
                data = pickle.dumps(("snapshot", "set", snapshot), protocol=5)
                f.write(_HDR.pack(len(data)) + data)
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
