"""Pluggable head-state persistence (GCS fault tolerance).

The reference backs its GCS tables with a storage abstraction —
in-memory or Redis (reference: gcs/store_client/redis_store_client.h:126,
gcs_table_storage.h:200) — so a head restart reloads cluster metadata
and nodes resubscribe (node_manager.proto:325 NotifyGCSRestart). The
TPU-native equivalent here is an append-only local journal: every
durable mutation (KV, actor registry, placement groups) appends one
pickled record; restart replays the journal and then compacts it to a
single snapshot record. No external service required — the journal file
on shared storage is the single-host analogue; the same interface admits
a Redis-protocol backend later.

Record format: length-prefixed pickle frames, `(table, op, payload)`.
A truncated tail (crash mid-append) is ignored on replay.
"""

from __future__ import annotations

import logging
import os
import pickle
import struct
import tempfile
from typing import Any, Iterator

logger = logging.getLogger("ray_tpu.head")

_HDR = struct.Struct("<I")


class FileJournal:
    """Append-only journal with replay + snapshot compaction.

    ``fsync=True`` makes every append durable against power loss (the
    reference's Redis equivalent is appendfsync always); the default
    flush-only survives process crashes, which is the head-FT threat
    model. ``size_bytes`` lets the owner trigger ONLINE compaction when
    KV churn grows the file (reference: Redis AOF rewrite) — restart
    replay also compacts, but a long-lived head must not wait for one.
    """

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = None
        self.fsync = fsync
        # While an async compaction's file rewrite runs off-thread,
        # appends land here and are replayed into the new file — they
        # must not hit the old inode mid-rename.
        self._buffering: list | None = None
        self._side_f = None
        try:
            self._nbytes = os.path.getsize(path)
        except OSError:
            self._nbytes = 0

    @property
    def size_bytes(self) -> int:
        return self._nbytes

    @property
    def _sidecar_path(self) -> str:
        return self.path + ".compacting"

    # ------------------------------------------------------------ write
    def append(self, record: tuple) -> None:
        data = pickle.dumps(record, protocol=5)
        if self._buffering is not None:
            # Mid-compaction. The durability promise of the current
            # mode must hold even now: the record also lands in a
            # sidecar (flushed always, fsynced under fsync mode) that
            # replay() consumes if we crash before the post-compaction
            # merge — the in-memory buffer alone would silently demote
            # crash durability during every compaction window.
            self._buffering.append(data)
            if self._side_f is None:
                self._side_f = open(self._sidecar_path, "ab")
            self._side_f.write(_HDR.pack(len(data)) + data)
            self._side_f.flush()
            if self.fsync:
                os.fsync(self._side_f.fileno())
            self._nbytes += _HDR.size + len(data)
            return
        if self._f is None:
            self._f = open(self.path, "ab")
        self._f.write(_HDR.pack(len(data)) + data)
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())
        self._nbytes += _HDR.size + len(data)

    # ------------------------------------------------------------- read
    def replay(self) -> Iterator[tuple]:
        """All intact records, oldest first; stops at a torn tail. A
        sidecar left by a crash mid-online-compaction replays after the
        main file (its records are strictly newer)."""
        for path in (self.path, self._sidecar_path):
            if not os.path.exists(path):
                continue
            with open(path, "rb") as f:
                while True:
                    hdr = f.read(_HDR.size)
                    if len(hdr) < _HDR.size:
                        break
                    (length,) = _HDR.unpack(hdr)
                    data = f.read(length)
                    if len(data) < length:
                        break  # torn append from a crash — discard
                    try:
                        yield pickle.loads(data)
                    except Exception:  # noqa: BLE001 - corrupt frame
                        logger.warning(
                            "journal replay stopped at a corrupt frame "
                            "(state up to this point is restored)"
                        )
                        break

    def compact(self, snapshot: Any) -> None:
        """Atomically replace the journal with one snapshot record."""
        self.close()
        self._write_snapshot(pickle.dumps(
            ("snapshot", "set", snapshot), protocol=5
        ))
        try:
            # Any crash-left sidecar is folded into this snapshot (the
            # caller replayed it); keeping it would double-apply.
            os.unlink(self._sidecar_path)
        except OSError:
            pass
        self._nbytes = os.path.getsize(self.path)

    def _write_snapshot(self, data: bytes) -> None:
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(self.path) or ".", prefix=".journal-"
        )
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(_HDR.pack(len(data)) + data)
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    async def compact_async(self, snapshot: Any) -> None:
        """Online compaction: the snapshot write + fsync + rename run
        off-thread so the head's event loop keeps serving RPCs
        (reference: Redis rewrites the AOF in a forked child for the
        same reason). Concurrent appends buffer in memory and replay
        into the fresh file afterwards."""
        import asyncio

        if self._buffering is not None:
            return  # one at a time
        data = pickle.dumps(("snapshot", "set", snapshot), protocol=5)
        self.close()
        self._buffering = []
        try:
            await asyncio.to_thread(self._write_snapshot, data)
        finally:
            buffered, self._buffering = self._buffering, None
            if self._side_f is not None:
                self._side_f.close()
                self._side_f = None
            self._f = open(self.path, "ab")
            for rec in buffered:
                self._f.write(_HDR.pack(len(rec)) + rec)
            self._f.flush()
            if self.fsync and buffered:
                os.fsync(self._f.fileno())
            try:
                os.unlink(self._sidecar_path)
            except OSError:
                pass
            self._nbytes = os.path.getsize(self.path)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
        if self._side_f is not None:
            self._side_f.close()
            self._side_f = None
