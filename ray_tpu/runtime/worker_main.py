"""Worker process entry point (reference: the default_worker.py the raylet
execs, python/ray/_private/workers/default_worker.py + worker_pool.h:280).

Spawned by the node manager with connection info in env vars; registers
back, then serves tasks until told to exit or the node dies.
"""

from __future__ import annotations

import asyncio
import os
import sys

if sys.flags.no_site:
    # Fast-start workers run with -S to skip the image's sitecustomize
    # (which imports the TPU plugin, ~1.7 s). Recover .pth-based packages
    # (editable installs, namespace hooks) by processing site dirs
    # explicitly — addsitedir executes .pth files but not sitecustomize.
    import site

    for _sp in site.getsitepackages():
        site.addsitedir(_sp)


async def main() -> None:
    from ray_tpu.runtime.core_worker import CoreWorker
    import ray_tpu.api as api

    # Process bootstrap: env is the only channel the spawning node
    # agent has into a fresh worker — no config registry exists yet.
    # tpulint: allow(TPU703 reason=worker bootstrap vars are passed by the spawner via env before any config exists)
    head_addr = os.environ["RAY_TPU_HEAD_ADDR"]
    # tpulint: allow(TPU703 reason=worker bootstrap vars are passed by the spawner via env before any config exists)
    node_addr = os.environ["RAY_TPU_NODE_ADDR"]
    # tpulint: allow(TPU703 reason=worker bootstrap vars are passed by the spawner via env before any config exists)
    store_dir = os.environ["RAY_TPU_STORE_DIR"]
    # tpulint: allow(TPU703 reason=worker bootstrap vars are passed by the spawner via env before any config exists)
    worker_id = os.environ["RAY_TPU_WORKER_ID"]

    core = CoreWorker(
        mode="worker",
        head_addr=head_addr,
        node_addr=node_addr,
        store_dir=store_dir,
        worker_id=worker_id,
    )
    addr = await core.start()
    api._attach_worker(core, asyncio.get_running_loop())
    await core.node.call(
        "register_worker", worker_id=worker_id, addr=addr, pid=os.getpid()
    )
    # Serve until the node connection drops (node death ⇒ worker exit).
    while not core.node._closed:
        await asyncio.sleep(0.5)
    sys.exit(0)


if __name__ == "__main__":
    asyncio.run(main())
