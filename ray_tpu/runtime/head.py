"""Head service: cluster-metadata authority (GCS equivalent).

Mirrors the reference's GCS server responsibilities (reference:
src/ray/gcs/gcs_server.h:100 — node table, actor registry, KV store,
pubsub, health checks, cluster-level scheduling) in one asyncio service.
State lives in process memory behind a tiny storage interface so a
Redis/file backend can slot in for fault tolerance (reference:
gcs/store_client/redis_store_client.h:126).
"""

from __future__ import annotations

import asyncio
import collections
import logging
import time
from typing import Any

from ray_tpu._private import rpc
from ray_tpu._private.ids import ActorID, NodeID

logger = logging.getLogger("ray_tpu.head")

class HeadService:
    def __init__(self, journal_path: str | None = None):
        self.server = rpc.Server(self._handle)
        self.addr: str | None = None
        # Durable-state journal (reference: Redis-backed GCS tables,
        # redis_store_client.h:126). Off unless a path is configured —
        # single-driver test clusters don't pay the fsync tax.
        if journal_path is None:
            from ray_tpu._private import config

            journal_path = config.get("HEAD_JOURNAL") or None
        self.journal = None
        if journal_path and journal_path != "off":
            from ray_tpu._private import config
            from ray_tpu.runtime.head_storage import FileJournal

            self.journal = FileJournal(
                journal_path, fsync=config.get("JOURNAL_FSYNC")
            )
        # node_id hex → {addr, resources, labels, last_seen, conn}
        self.nodes: dict[str, dict] = {}
        # node_id hex → {reason, deadline_ts, since}: DRAINING nodes.
        # A draining node stays in the node table (its leases keep
        # running, its heartbeats keep counting) but receives no new
        # task leases, placements, or bundles; the notice fans out on
        # pubsub so workers learn BEFORE the node dies. Journaled: a
        # head restart must not resurrect a preempting node into the
        # schedulable pool.
        self.draining: dict[str, dict] = {}
        self.kv: dict[str, bytes] = {}
        # actor_id hex → {name, state, addr, node_id, class_name}
        self.actors: dict[str, dict] = {}
        self.named_actors: dict[str, str] = {}  # name → actor_id hex
        # channel → set[Connection]
        self.subs: dict[str, set[rpc.Connection]] = {}
        # pg_id → {bundles: [dict], strategy, nodes: [node_id per bundle]}
        self.placement_groups: dict[str, dict] = {}
        # head-initiated client conns to each node (for PG prepare/commit)
        self._node_conns: dict[str, rpc.Connection] = {}
        self._reaper: asyncio.Task | None = None
        # Task-event store (reference: GcsTaskManager gcs_task_manager.h:97
        # buffers worker-flushed task state transitions for the state API
        # and `ray timeline`). Ring-bounded; per-task latest state capped.
        self.task_events: collections.deque = collections.deque(maxlen=20000)
        self.task_latest: collections.OrderedDict = collections.OrderedDict()
        # worker addr → latest metrics snapshot {name: record}
        self.metrics: dict[str, dict] = {}
        # Per-train-job goodput accounting, folded from rank-0
        # "train:step" SPAN events as they arrive on the task-event
        # pipeline: productive step time vs. time lost to stalls
        # (inter-step gaps, data wait, checkpointing) and to elastic
        # attempt restarts (gap between the last step of attempt N and
        # the first step of attempt N+1).
        self.train_runs: dict[str, dict] = {}
        # Per-deployment serve SLO ledger, folded from "serve:ingress"
        # SPAN events the same way train_runs folds "train:step":
        # request/error counts, sliding TTFT/latency windows, SLO
        # attainment over SERVE_SLO_WINDOW_S, and a burn-rate alert
        # (ray_tpu_serve_slo_alert) with an OFF→ON warn log. Keyed
        # "app/deployment".
        self.serve_runs: dict[str, dict] = {}
        # Controller autoscale reports ("app/deployment" → target/
        # desired/replicas/draining/reason/ts): the decisions the serve
        # control loop derived from this ledger, surfaced back through
        # serve_stats and the head-owned target-replicas gauge so they
        # survive controller restarts.
        self.serve_autoscale: dict[str, dict] = {}
        # Device-memory ledger, folded from "mem:sample" SPAN events
        # the same way the goodput/SLO ledgers fold theirs: per-node
        # current/peak used bytes, capacity, headroom alert state (with
        # OFF→ON warn log), and per-job peaks — surfaced via the
        # mem_stats RPC, /api/memory, and `ray_tpu mem`.
        self.mem_nodes: dict[str, dict] = {}
        self.mem_jobs: dict[str, dict] = {}
        # Compiled-program profiler ledger, folded from rank-0
        # "profile:step" SPAN events: per-job latest MFU decomposition
        # (compute_floor/comm_in_program/hbm_bound/host_gap/
        # unattributed shares + dominant gap), surfaced next to the
        # goodput numbers. profile_fp holds the per-step-signature
        # baseline fingerprints the regression sentinel compares new
        # captures against — journaled, so a head restart cannot
        # forget what "normal" looked like.
        self.profile_runs: dict[str, dict] = {}
        self.profile_fp: dict[str, dict] = {}
        # Collective-group membership (the fault-tolerance layer's view):
        # group → {"epoch": int, "members": {rank: {addr, node_addr,
        # worker_id, dead}}}. Node/worker death fans out to survivors on
        # the "collective" pubsub channel so in-flight ops abort instead
        # of burning their full deadline.
        self.collective_members: dict[str, dict] = {}
        # node_id → partial-collective skips escalated by hubs
        # (collective_straggler_report): merged into the chronic-
        # straggler signal and — with COLLECTIVE_SKIP_DRAIN — acted on
        # directly via the drain path.
        self.chronic_skip_reports: dict[str, float] = {}
        # Slice fault domains: slice label → {"nodes": [node_id],
        # "state": healthy|draining|dead, "reason", "since"}. Membership
        # comes from node registrations (the "slice" label); state is
        # journaled like the drain table — a head restart must not
        # forget that a slice was mid-drain (its nodes' DRAINING
        # tombstones survive too, but the SLICE state is what stops the
        # escalation logic from re-firing and what operators see). Real
        # pods fail slice-at-a-time (a GKE maintenance event takes all
        # hosts of a slice atomically), so one host's preemption or
        # death drains the WHOLE slice and the autoscaler replaces the
        # slice as a unit.
        self.slices: dict[str, dict] = {}
        # Cluster-wide infeasible lease demand, deduped per waiting
        # request: requester id → (resources, ts). Each spill-waiting
        # request refreshes its single entry, so one pending lease reads
        # as ONE demand unit, and entries age out seconds after the
        # requester stops polling (granted or gave up).
        self.unschedulable: dict[str, tuple[dict, float]] = {}
        # Distributed checkpoint metadata (the shard store's authority):
        # run → step → {"world", "ranks": {rank: {"entries", "metrics",
        # "ts"}}, "complete_ts"}. A checkpoint EXISTS once every rank of
        # its world has committed — partial shard sets are invisible to
        # restore. Journaled (like the drain table) so replica state
        # survives a head restart.
        self.checkpoints: dict[str, dict[int, dict]] = {}
        # chunk hash → set of node addrs holding a replica.
        self.ckpt_locations: dict[str, set[str]] = {}
        # Sweep-engine table (the Tune orchestrator's durable state):
        # sweep_id → {"trials": {trial_id: {state, config, rung, job,
        # forked_from, node, ...}}, plus orchestrator-reported meta
        # (scheduler, num_samples, forks/preemptions counters, ts).
        # Journaled like the drain/slice tables — a head SIGKILL
        # mid-sweep must not forget which trials were stopped at a rung
        # or which manifest a fork descended from, or the restarted
        # orchestrator would re-run killed trials and double-count
        # population exploits.
        self.sweeps: dict[str, dict] = {}
        self._ckpt_repairing = False
        self._ckpt_last_repair = 0.0
        # Vectorized scheduling columns: per-resource-kind numpy views
        # over a stable node ordering, rebuilt on membership change and
        # updated in place on each resource sync. The label-free pick
        # (the hot path under actor/PG storms) scans these instead of
        # per-node Python dicts — profiled 50→100-node sublinearity was
        # dominated by that scan (PROFILE_r05.md). None = rebuild.
        # Drain/undrain/death flip an `eligible` mask in place (O(1))
        # instead of invalidating — a mass-drain storm interleaved with
        # picks was O(nodes²) in rebuilds.
        self._sched_cols: dict | None = None
        # --- control-plane overload protection ---
        # Admission classes on the dispatch path: control RPCs
        # (keepalive/register/sync/probes) execute immediately;
        # telemetry (add_task_events) enqueues here and a background
        # worker folds it, so a span flood can never starve liveness.
        # Bounded: under sustained overload the OLDEST events shed
        # (freshest telemetry wins) with ray_tpu_head_shed_total
        # counting and an OFF→ON overload alert.
        self._fold_queue: collections.deque = collections.deque()
        self._fold_wakeup = asyncio.Event()
        self._fold_task: asyncio.Task | None = None
        self._shed_total = 0
        self._folded_total = 0
        self._overload_alert = False
        # Pubsub coalescing: publishes buffer per channel and flush once
        # per event-loop tick (or per _pub_batch section), so a
        # correlated-failure storm costs O(subscribers) PUSH frames
        # instead of O(events × subscribers).
        self._pub_pending: dict[str, list] = {}
        self._pub_flush_scheduled = False
        self._pub_batch_depth = 0
        self._pub_msgs_total = 0    # logical messages published
        self._pub_pushes_total = 0  # PUSH frames actually sent
        # node_id → slice label reverse index: _slice_of was an
        # O(slices × nodes) scan and mass death makes it hot.
        self._slice_index: dict[str, str] = {}
        # Journal accounting (watermark-driven snapshot cadence +
        # the head_stats surface).
        self._journal_floor = 0
        self._compacting = False
        self._last_compaction_ts: float | None = None
        self._replayed_records = 0
        self._replay_s = 0.0
        self._started_ts = time.time()

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        if self.journal is not None:
            self._restore_from_journal()
        p = await self.server.start(host, port)
        self.addr = f"{host}:{p}"
        self._reaper = asyncio.ensure_future(self._health_loop())
        return self.addr

    # --------------------------------------------------------- journal
    def _journal_append(self, table: str, op: str, payload) -> None:
        if self.journal is None:
            return
        self.journal.append((table, op, payload))
        # Online compaction (reference: Redis AOF rewrite): KV churn on
        # a long-lived head must not grow the journal without bound.
        # The 2× floor guard keeps a state set LARGER than the
        # threshold from compacting on every append; the write itself
        # runs off-loop (compact_async) so RPC serving never stalls.
        from ray_tpu._private import config

        size = self.journal.size_bytes
        floor = getattr(self, "_journal_floor", 0)
        due = (
            size > config.get("JOURNAL_COMPACT_BYTES")
            and size > 2 * floor
        )
        # Table-size watermark: when the snapshot itself is large (the
        # 1000-node regime), the 2× floor guard alone lets the replay
        # TAIL grow to `floor` bytes before compacting — restart replay
        # then costs snapshot + an equally large tail. Compacting once
        # the tail alone passes the watermark bounds replay depth
        # independently of table size.
        watermark = config.get("HEAD_SNAPSHOT_WATERMARK_BYTES")
        if watermark > 0 and size - floor > watermark:
            due = True
        if due and not getattr(self, "_compacting", False):
            self._compacting = True
            asyncio.ensure_future(self._compact_bg())

    async def _compact_bg(self) -> None:
        try:
            await self.journal.compact_async(self._snapshot())
        except Exception:  # noqa: BLE001 - keep serving (e.g. disk full)
            logger.warning("journal compaction failed", exc_info=True)
        finally:
            # Raise the floor EVEN ON FAILURE: the next attempt then
            # needs 2× further growth, so a persistently failing disk
            # doesn't re-trigger a full-snapshot pickle on every append.
            self._journal_floor = self.journal.size_bytes
            self._last_compaction_ts = time.time()
            self._compacting = False

    def _restore_from_journal(self) -> None:
        """Replay durable tables (KV, actors, PGs), then compact to one
        snapshot. Node/subscriber state is NOT persisted: nodes
        re-register through their reconnecting heartbeat (the
        NotifyGCSRestart equivalent) and re-dial their subscriptions."""
        t0 = time.monotonic()
        replayed = 0
        for table, op, payload in self.journal.replay():
            replayed += 1
            if table == "snapshot" and op == "set":
                self.kv = dict(payload["kv"])
                self.actors = {
                    aid: dict(a) for aid, a in payload["actors"].items()
                }
                self.named_actors = dict(payload["named_actors"])
                self.placement_groups = {
                    pid: dict(pg)
                    for pid, pg in payload["placement_groups"].items()
                }
                self.draining = {
                    nid: dict(d)
                    for nid, d in payload.get("draining", {}).items()
                }
                self.checkpoints = {
                    run: {int(s): dict(rec) for s, rec in steps.items()}
                    for run, steps in payload.get(
                        "checkpoints", {}
                    ).items()
                }
                self.ckpt_locations = {
                    h: set(addrs)
                    for h, addrs in payload.get(
                        "ckpt_locations", {}
                    ).items()
                }
                self.slices = {
                    sid: dict(rec)
                    for sid, rec in payload.get("slices", {}).items()
                }
                self.profile_fp = {
                    sig: dict(rec)
                    for sig, rec in payload.get(
                        "profile_fp", {}
                    ).items()
                }
                self.sweeps = {
                    sid: {
                        **{
                            k: v
                            for k, v in rec.items()
                            if k != "trials"
                        },
                        "trials": {
                            tid: dict(t)
                            for tid, t in rec.get(
                                "trials", {}
                            ).items()
                        },
                    }
                    for sid, rec in payload.get("sweeps", {}).items()
                }
            elif table == "sweep":
                if op == "put":
                    rec = self.sweeps.setdefault(
                        payload["sweep_id"], {"trials": {}}
                    )
                    fields = dict(payload["fields"])
                    fields.pop("trials", None)
                    rec.update(fields)
                elif op == "trial":
                    rec = self.sweeps.setdefault(
                        payload["sweep_id"], {"trials": {}}
                    )
                    rec["trials"].setdefault(
                        payload["trial_id"], {}
                    ).update(payload["fields"])
                else:
                    self.sweeps.pop(payload["sweep_id"], None)
            elif table == "profile":
                if op == "put":
                    self.profile_fp[payload["sig"]] = dict(
                        payload["fields"]
                    )
                else:
                    self.profile_fp.pop(payload["sig"], None)
            elif table == "slice":
                if op == "put":
                    self.slices[payload["slice_id"]] = dict(
                        payload["fields"]
                    )
                else:
                    self.slices.pop(payload["slice_id"], None)
            elif table == "ckpt":
                self._ckpt_replay(op, payload)
            elif table == "drain":
                if op == "put":
                    self.draining[payload["node_id"]] = dict(
                        payload["fields"]
                    )
                else:
                    self.draining.pop(payload["node_id"], None)
            elif table == "kv":
                if op == "put":
                    self.kv[payload["key"]] = payload["value"]
                else:
                    self.kv.pop(payload["key"], None)
            elif table == "actor":
                aid = payload["actor_id"]
                if op == "put":
                    self.actors[aid] = dict(payload["fields"])
                    name = payload["fields"].get("name")
                    if name:
                        self.named_actors[name] = aid
                elif op == "update" and aid in self.actors:
                    self.actors[aid].update(payload["fields"])
            elif table == "pg":
                if op == "put":
                    self.placement_groups[payload["pg_id"]] = dict(
                        payload["fields"]
                    )
                else:
                    self.placement_groups.pop(payload["pg_id"], None)
        self.journal.compact(self._snapshot())
        self._journal_floor = self.journal.size_bytes
        self._last_compaction_ts = time.time()
        self._replayed_records = replayed
        self._replay_s = time.monotonic() - t0
        # Restored slice membership repopulates the reverse index.
        self._slice_index = {
            nid: sid
            for sid, rec in self.slices.items()
            for nid in rec.get("nodes", ())
        }

    def _snapshot(self) -> dict:
        return {
            "kv": dict(self.kv),
            "actors": {
                aid: self._durable_actor(a)
                for aid, a in self.actors.items()
            },
            "named_actors": dict(self.named_actors),
            "placement_groups": {
                pid: dict(pg)
                for pid, pg in self.placement_groups.items()
            },
            "draining": {
                nid: dict(d) for nid, d in self.draining.items()
            },
            "checkpoints": {
                run: {s: dict(rec) for s, rec in steps.items()}
                for run, steps in self.checkpoints.items()
            },
            "ckpt_locations": {
                h: sorted(addrs)
                for h, addrs in self.ckpt_locations.items()
            },
            "slices": {
                sid: dict(rec) for sid, rec in self.slices.items()
            },
            "profile_fp": {
                sig: dict(rec)
                for sig, rec in self.profile_fp.items()
            },
            "sweeps": {
                sid: {
                    **{k: v for k, v in rec.items() if k != "trials"},
                    "trials": {
                        tid: dict(t)
                        for tid, t in rec.get("trials", {}).items()
                    },
                }
                for sid, rec in self.sweeps.items()
            },
        }

    @staticmethod
    def _durable_actor(actor: dict) -> dict:
        """Actor fields safe to pickle (no asyncio lock)."""
        return {k: v for k, v in actor.items() if k != "_restart_lock"}

    async def stop(self):
        if self._reaper:
            self._reaper.cancel()
        if self._fold_task:
            self._fold_task.cancel()
        await self.server.stop()
        if self.journal is not None:
            self.journal.close()

    # ------------------------------------------------------------ pubsub
    def publish(self, channel: str, msg: Any):
        """Queue one pubsub message. Delivery coalesces per channel per
        event-loop tick: N messages to a channel inside one tick reach
        each subscriber as ONE batched PUSH frame (subscribers unpack
        in order), so a 32-node slice death costs O(subscribers)
        frames, not O(nodes × subscribers)."""
        self._pub_msgs_total += 1
        if not self.subs.get(channel):
            return
        self._pub_pending.setdefault(channel, []).append(msg)
        if self._pub_flush_scheduled or self._pub_batch_depth > 0:
            return
        self._pub_flush_scheduled = True
        try:
            asyncio.get_running_loop().call_soon(self._flush_publishes)
        except RuntimeError:
            # No running loop (handlers driven directly in unit tests):
            # deliver inline.
            self._flush_publishes()

    def _pub_batch(self):
        """Context manager holding pubsub flushes open across an
        await-ful multi-node event (slice drain escalation, mass reap)
        so the whole storm coalesces even though the loop runs between
        its awaits."""
        import contextlib

        @contextlib.contextmanager
        def hold():
            self._pub_batch_depth += 1
            try:
                yield
            finally:
                self._pub_batch_depth -= 1
                if self._pub_batch_depth == 0 and self._pub_pending:
                    self._flush_publishes()

        return hold()

    def _flush_publishes(self) -> None:
        self._pub_flush_scheduled = False
        if self._pub_batch_depth > 0:
            return  # a batch section is open; it flushes on exit
        pending, self._pub_pending = self._pub_pending, {}
        for channel, msgs in pending.items():
            subs = list(self.subs.get(channel, ()))
            if not subs:
                continue
            if len(msgs) == 1:
                frame = {"channel": channel, "msg": msgs[0]}
            else:
                frame = {"channel": channel, "batch": msgs}
            for conn in subs:
                self._pub_pushes_total += 1
                conn.push(frame)

    # ----------------------------------------------------------- handler
    async def _handle(self, method: str, kw: dict, conn: rpc.Connection):
        from ray_tpu._private.test_utils import head_stall_for

        stall = head_stall_for(method)
        if stall > 0:
            await asyncio.sleep(stall)
        fn = getattr(self, f"_on_{method}", None)
        if fn is None:
            raise rpc.RpcError(f"head: unknown method {method!r}")
        return await fn(conn=conn, **rpc.tolerant_kwargs(fn, kw))

    async def _on_register_node(
        self,
        conn,
        node_id: str,
        addr: str,
        resources: dict,
        available: dict | None = None,
        res_version: int = 0,
        labels=None,
        agent_addr=None,
    ):
        self.nodes[node_id] = {
            "addr": addr,
            "resources": dict(resources),
            # A RE-registration (head reconnect) carries the node's live
            # view; defaulting to full totals would over-schedule onto
            # leases the head just forgot about.
            "available": dict(available if available is not None else resources),
            "res_version": res_version,
            "labels": labels or {},
            "agent_addr": agent_addr,
            "last_seen": time.monotonic(),
            "conn": conn,
        }
        conn.state["node_id"] = node_id
        # A RE-registration (reconnect storm after a head restart)
        # updates the maintained columns in place; only a genuinely new
        # node or resource kind forces a rebuild — a 1000-node
        # registration herd with interleaved picks must not rebuild
        # O(nodes)-sized columns per register.
        cols = self._sched_cols
        if cols is not None:
            i = cols["idx"].get(node_id)
            node = self.nodes[node_id]
            kinds = set(node["resources"]) | set(node["available"])
            if i is not None and all(k in cols["total"] for k in kinds):
                for k in cols["total"]:
                    cols["total"][k][i] = float(
                        node["resources"].get(k, 0)
                    )
                    cols["avail"][k][i] = float(
                        node["available"].get(k, 0)
                    )
                cols["eligible"][i] = node_id not in self.draining
            else:
                self._sched_cols = None  # membership changed
        self._slice_register(node_id, labels or {})
        old = self._node_conns.pop(node_id, None)
        if old is not None:
            await old.close()
        self._node_conns[node_id] = await rpc.connect(addr)
        if node_id in self.draining:
            # A draining node re-registering (head restart, conn blip)
            # must come back DRAINING on both sides: re-push the flag so
            # its local lease path keeps refusing work.
            d = self.draining[node_id]
            asyncio.ensure_future(
                self._push_set_draining(node_id, d)
            )
        self.publish("node", {"event": "added", "node_id": node_id, "addr": addr})
        return {"ok": True}

    async def _push_set_draining(self, node_id: str, d: dict):
        conn = self._node_conns.get(node_id)
        if conn is None:
            return
        try:
            await conn.call(
                "set_draining",
                draining=True,
                reason=d.get("reason", ""),
                deadline_ts=d.get("deadline_ts"),
            )
        # tpulint: allow(broad-except reason=the node may be mid-death; the pubsub fan-out already carried the notice, this direct push is belt-and-suspenders)
        except Exception:
            pass

    async def _on_sync(
        self,
        conn,
        node_id: str,
        version: int,
        available: dict,
        pending: list | None = None,
    ):
        """Versioned resource-view update, pushed by nodes ON CHANGE
        (reference: ray_syncer.h:90 versioned component messages). A
        stale version (reordered across a reconnect) is ignored rather
        than rolling the view backwards."""
        node = self.nodes.get(node_id)
        if node is None:
            return {"ok": False, "reregister": True}
        node["last_seen"] = time.monotonic()
        if version < node.get("res_version", -1):
            return {"ok": True, "stale": True}
        node["res_version"] = version
        node["available"] = available
        node["pending"] = pending or []
        # Draining nodes stay IN the columns behind the eligible mask
        # (drain/undrain flip one bit instead of invalidating), so
        # their syncs update in place like everyone else's.
        cols = self._sched_cols
        if cols is not None:
            i = cols["idx"].get(node_id)
            if i is None or any(k not in cols["avail"] for k in available):
                self._sched_cols = None  # new node/kind: full rebuild
            else:
                for k, col in cols["avail"].items():
                    col[i] = available.get(k, 0.0)
        return {"ok": True}

    async def _on_keepalive(self, conn, node_id: str):
        """Liveness-only tick for an unchanged resource view."""
        node = self.nodes.get(node_id)
        if node is None:
            return {"ok": False, "reregister": True}
        node["last_seen"] = time.monotonic()
        return {"ok": True}

    async def _on_cluster_status(self, conn):
        """Autoscaler poll: per-node totals/available/pending demand
        (reference: GcsAutoscalerStateManager.GetClusterResourceState)."""
        self._expire_unschedulable()
        return {
            "unschedulable": [r for r, _ts in self.unschedulable.values()],
            "draining": {
                nid: dict(d) for nid, d in self.draining.items()
            },
            "slices": {
                sid: dict(rec) for sid, rec in self.slices.items()
            },
            # Serve control-plane state (controller autoscale reports):
            # rides the same poll so the cluster autoscaler sees replica
            # deficits next to the node demand that will absorb them.
            "serve_autoscale": {
                key: dict(rec)
                for key, rec in self.serve_autoscale.items()
            },
            "nodes": {
                nid: {
                    "addr": n["addr"],
                    "resources": n["resources"],
                    "available": n["available"],
                    "pending": n.get("pending", []),
                    "labels": n.get("labels", {}),
                }
                for nid, n in self.nodes.items()
            }
        }

    async def _on_node_table(self, conn):
        return {
            nid: {k: v for k, v in n.items() if k != "conn"}
            for nid, n in self.nodes.items()
        }

    async def _on_get_node(self, conn, node_id: str):
        node = self.nodes.get(node_id)
        if node is None:
            return {"ok": False, "error": f"no node {node_id[:12]}…"}
        return {
            "ok": True,
            "node_id": node_id,
            "addr": node["addr"],
            "labels": node.get("labels", {}),
        }

    # ------------------------------------------------------ node drain
    async def _on_drain_node(
        self,
        conn,
        node_id: str,
        reason: str = "",
        deadline_s: float | None = None,
    ):
        """Move a node to DRAINING: excluded from every placement path
        (pick_node, placement groups, actor restarts) while its existing
        leases keep running, with the notice fanned out on pubsub so
        workers learn before the node dies. Idempotent — the first
        notice's deadline wins (a preemption clock does not restart)."""
        node = self.nodes.get(node_id)
        if node is None:
            return {"ok": False, "error": f"unknown node {node_id[:12]}…"}
        rec = self.draining.get(node_id)
        if rec is not None:
            return {"ok": True, "already": True, **rec}
        from ray_tpu._private import config

        if deadline_s is None:
            deadline_s = config.get("DRAIN_DEADLINE_S")
        now = time.time()
        rec = self.draining[node_id] = {
            "reason": reason,
            "deadline_ts": now + float(deadline_s),
            "since": now,
        }
        self._journal_append(
            "drain", "put", {"node_id": node_id, "fields": dict(rec)}
        )
        self._sched_set_eligible(node_id, False)
        self.publish(
            "node",
            {
                "event": "draining",
                "node_id": node_id,
                "addr": node["addr"],
                "reason": reason,
                "deadline_ts": rec["deadline_ts"],
            },
        )
        # Reuse the death fan-out channel: every process watching for
        # collective member deaths learns about the drain with no extra
        # subscription — this is what gives train workers their
        # emergency-checkpoint window.
        self.publish(
            "collective",
            {
                "event": "node_draining",
                "node_id": node_id,
                "node_addr": node["addr"],
                "reason": reason,
                "deadline_s": float(deadline_s),
                "deadline_ts": rec["deadline_ts"],
            },
        )
        await self._push_set_draining(node_id, rec)
        # Drain-aware checkpoint evacuation: chunks whose only replicas
        # live on this node must re-replicate INSIDE the notice window.
        self._schedule_ckpt_repair()
        # Slice fault domain: one host draining means the slice is
        # going away — drain its siblings inside the same window.
        await self._maybe_drain_slice(node_id, reason, deadline_s)
        return {"ok": True, **rec}

    async def _on_undrain_node(self, conn, node_id: str):
        """Cancel a drain (maintenance event cleared, operator abort):
        the node rejoins the schedulable pool."""
        rec = self.draining.pop(node_id, None)
        if rec is None:
            return {"ok": False}
        self._journal_append("drain", "del", {"node_id": node_id})
        self._sched_set_eligible(node_id, True)
        node = self.nodes.get(node_id)
        addr = node["addr"] if node else None
        self.publish(
            "node",
            {"event": "undrained", "node_id": node_id, "addr": addr},
        )
        self.publish(
            "collective",
            {"event": "node_undrain", "node_id": node_id, "node_addr": addr},
        )
        conn_ = self._node_conns.get(node_id)
        if conn_ is not None:
            try:
                await conn_.call("set_draining", draining=False)
            # tpulint: allow(broad-except reason=node may be mid-death; the undrain event already fanned out on pubsub and the table is authoritative)
            except Exception:
                pass
        # Slice state follows its members: once the last draining member
        # of a DRAINING slice is undrained, the slice is healthy again
        # (maintenance event cleared for the whole unit).
        sid = self._slice_of(node_id)
        if sid is not None:
            srec = self.slices[sid]
            if srec["state"] == "draining" and not any(
                n in self.draining for n in srec["nodes"]
            ):
                srec["state"] = "healthy"
                srec["reason"] = ""
                self._slice_journal(sid)
        return {"ok": True}

    async def _on_drain_table(self, conn):
        return {
            "draining": {nid: dict(d) for nid, d in self.draining.items()}
        }

    # ---------------------------------------------- slice fault domains
    def _slice_journal(self, slice_id: str) -> None:
        rec = self.slices.get(slice_id)
        if rec is None:
            self._journal_append("slice", "del", {"slice_id": slice_id})
        else:
            self._journal_append(
                "slice", "put",
                {"slice_id": slice_id, "fields": dict(rec)},
            )

    def _slice_register(self, node_id: str, labels: dict) -> None:
        """Fold one node registration into the slice table. A node of a
        DEAD slice re-registering revives the slice (a replacement
        booted under the same label); a node of a DRAINING slice stays
        draining — its per-node tombstone is re-pushed by the caller."""
        slice_id = (labels or {}).get("slice")
        if not slice_id:
            return
        rec = self.slices.get(slice_id)
        if rec is None or rec.get("state") == "dead":
            rec = self.slices[slice_id] = {
                "nodes": [],
                "state": "healthy",
                "reason": "",
                "since": time.time(),
            }
        if node_id not in rec["nodes"]:
            rec["nodes"].append(node_id)
            self._slice_journal(slice_id)
        self._slice_index[node_id] = slice_id

    def _slice_of(self, node_id: str) -> str | None:
        # O(1) via the maintained reverse index (the full scan was
        # O(slices × nodes) and mass death makes this hot); the scan
        # below only runs to self-heal a stale miss.
        sid = self._slice_index.get(node_id)
        if sid is not None:
            rec = self.slices.get(sid)
            if rec is not None and node_id in rec["nodes"]:
                return sid
            self._slice_index.pop(node_id, None)
        for sid, rec in self.slices.items():
            if node_id in rec["nodes"]:
                self._slice_index[node_id] = sid
                return sid
        return None

    async def _maybe_drain_slice(
        self, node_id: str, reason: str, deadline_s: float | None = None
    ) -> None:
        """Whole-slice drain escalation: one host of a slice draining
        means the slice is going away (GCE maintenance and preemption
        reap slices atomically) — drain every sibling host NOW so their
        work migrates inside the same notice window, and mark the slice
        DRAINING so the autoscaler provisions one replacement slice,
        not a node at a time."""
        from ray_tpu._private import config

        if not config.get("SLICE_FAULT_DOMAINS"):
            return
        slice_id = self._slice_of(node_id)
        if slice_id is None:
            return
        rec = self.slices[slice_id]
        if rec["state"] in ("draining", "dead"):
            return  # escalation already ran (or there is nothing left)
        rec["state"] = "draining"
        rec["reason"] = reason
        rec["since"] = time.time()
        self._slice_journal(slice_id)
        logger.warning(
            "slice %s: host %s is going away (%s); draining the whole "
            "slice (%d hosts)",
            slice_id, node_id[:12], reason, len(rec["nodes"]),
        )
        # One batch section for the whole escalation: the slice notice
        # plus every sibling's draining events reach each subscriber as
        # one coalesced PUSH per channel, not O(hosts × subscribers)
        # frames.
        with self._pub_batch():
            self.publish(
                "collective",
                {
                    "event": "slice_draining",
                    "slice_id": slice_id,
                    "nodes": list(rec["nodes"]),
                    "reason": reason,
                },
            )
            # The anchor node is included too when not already draining
            # (the death path escalates via a SURVIVING sibling as
            # anchor).
            for sibling in list(rec["nodes"]):
                if sibling in self.draining or sibling not in self.nodes:
                    continue
                await self._on_drain_node(
                    None,
                    node_id=sibling,
                    reason=f"slice {slice_id} fault domain: {reason}",
                    deadline_s=deadline_s,
                )

    def _slice_node_gone(self, node_id: str) -> tuple[str, dict] | None:
        """Drop a dead node from its slice's membership; returns the
        (slice_id, record) when the node belonged to one. A slice whose
        last host died is marked DEAD (kept for observability until a
        replacement registers under the label)."""
        slice_id = self._slice_of(node_id)
        if slice_id is None:
            return None
        rec = self.slices[slice_id]
        rec["nodes"].remove(node_id)
        self._slice_index.pop(node_id, None)
        if not rec["nodes"]:
            rec["state"] = "dead"
            rec["since"] = time.time()
        self._slice_journal(slice_id)
        return slice_id, rec

    async def _on_slice_table(self, conn):
        return {
            "slices": {
                sid: dict(rec) for sid, rec in self.slices.items()
            }
        }

    async def _on_collective_slice_report(
        self,
        conn,
        group: str,
        slice_id: str,
        skips: int = 0,
        window_s: float = 0.0,
    ):
        """The hierarchical allreduce escalated a chronically skipped
        SLICE: its DCN-hop skip rate crossed the sliding-window
        threshold. Resolve the slice (label match first, then
        positional index against the sorted table — the collective
        layer sees slice indices, not labels) and — unless
        COLLECTIVE_SKIP_DRAIN is off — drain the whole slice: the
        slice-level twin of collective_straggler_report."""
        from ray_tpu._private import config

        sid = slice_id if slice_id in self.slices else None
        if sid is None:
            try:
                ordered = sorted(self.slices)
                idx = int(slice_id)
                if 0 <= idx < len(ordered):
                    sid = ordered[idx]
            except (TypeError, ValueError):
                sid = None
        if sid is None:
            return {
                "ok": False,
                "error": f"cannot resolve slice {slice_id!r} of group "
                         f"{group!r} to a registered slice",
            }
        logger.warning(
            "slice %s (group %r) was skipped by %d hierarchical "
            "DCN-partial collectives in %.0fs: chronic slice straggler",
            sid, group, int(skips), window_s,
        )
        drained = False
        rec = self.slices[sid]
        if (
            config.get("COLLECTIVE_SKIP_DRAIN")
            and rec["state"] == "healthy"
        ):
            anchor = next(
                (n for n in rec["nodes"] if n in self.nodes), None
            )
            if anchor is not None:
                reply = await self._on_drain_node(
                    conn,
                    node_id=anchor,
                    reason=(
                        f"chronic slice straggler: {int(skips)} DCN-"
                        f"partial skips in {window_s:.0f}s"
                    ),
                )
                drained = bool(reply.get("ok"))
        return {"ok": True, "slice_id": sid, "drained": drained}

    # ------------------------------------------- distributed checkpoints
    def _ckpt_replay(self, op: str, payload: dict) -> None:
        """Fold one journaled "ckpt" op back into the tables."""
        if op == "commit":
            self._ckpt_apply_commit(**payload)
        elif op == "loc":
            self.ckpt_locations.setdefault(
                payload["chunk"], set()
            ).update(payload["addrs"])
        elif op == "loc_many":
            for chunk in payload["chunks"]:
                self.ckpt_locations.setdefault(chunk, set()).add(
                    payload["addr"]
                )
        elif op == "loc_del":
            locs = self.ckpt_locations.get(payload["chunk"])
            if locs is not None:
                locs.difference_update(payload["addrs"])
                if not locs:
                    self.ckpt_locations.pop(payload["chunk"], None)
        elif op == "prune":
            steps = self.checkpoints.get(payload["run"])
            if steps is not None:
                steps.pop(payload["step"], None)
                if not steps:
                    self.checkpoints.pop(payload["run"], None)

    def _ckpt_apply_commit(
        self, run, step, rank, world, entries, metrics=None, ts=None,
        parity=None,
    ) -> bool:
        """Fold one rank's manifest; returns True when this commit
        COMPLETES the checkpoint (every rank of its world committed)."""
        steps = self.checkpoints.setdefault(run, {})
        rec = steps.setdefault(
            step, {"world": int(world), "ranks": {}, "complete_ts": None}
        )
        if rec["world"] != int(world):
            # A retry attempt re-saving the same step at a new world
            # size supersedes the old shape — stale ranks would make
            # completeness undecidable.
            rec["world"] = int(world)
            rec["ranks"] = {}
            rec["complete_ts"] = None
        rec["ranks"][int(rank)] = {
            "entries": list(entries),
            "parity": list(parity or ()),
            "metrics": dict(metrics or {}),
            "ts": ts if ts is not None else time.time(),
        }
        if rec["complete_ts"] is None and set(range(rec["world"])) <= set(
            rec["ranks"]
        ):
            rec["complete_ts"] = ts if ts is not None else time.time()
            return True
        return False

    async def _on_ckpt_commit(
        self,
        conn,
        run: str,
        step: int,
        rank: int,
        world: int,
        entries: list,
        locations: dict | None = None,
        metrics: dict | None = None,
        parity: list | None = None,
    ):
        """Commit one rank's shard manifest. The checkpoint becomes
        visible to restore only once all ranks commit — this is the
        consistency protocol: manifest commit = checkpoint exists."""
        now = time.time()
        completed = self._ckpt_apply_commit(
            run, int(step), int(rank), int(world), entries, metrics, now,
            parity,
        )
        self._journal_append(
            "ckpt",
            "commit",
            {
                "run": run,
                "step": int(step),
                "rank": int(rank),
                "world": int(world),
                "entries": list(entries),
                "parity": list(parity or ()),
                "metrics": dict(metrics or {}),
                "ts": now,
            },
        )
        for chunk, addrs in (locations or {}).items():
            known = self.ckpt_locations.setdefault(chunk, set())
            fresh = [a for a in addrs if a and a not in known]
            if fresh:
                known.update(fresh)
                self._journal_append(
                    "ckpt", "loc", {"chunk": chunk, "addrs": fresh}
                )
        if completed:
            self._ckpt_prune(run)
        rec = self.checkpoints[run][int(step)]
        return {
            "ok": True,
            "complete": rec["complete_ts"] is not None,
            "ranks": len(rec["ranks"]),
            "world": rec["world"],
        }

    async def _on_ckpt_fork(
        self, conn, run: str, new_run: str, step: int | None = None
    ):
        """Fork a complete checkpoint into a new run lineage by
        re-committing its per-rank manifests under ``new_run``. The
        chunk store is content-addressed, so a fork moves ZERO bulk
        bytes — both manifests reference the same chunk hashes and the
        replica/location tables already cover them. This is the PBT
        exploit primitive: copy the winner's manifest, perturb the
        hyperparameters, keep training."""
        from ray_tpu.checkpoint.manifest import manifest_chunks

        steps = self.checkpoints.get(run) or {}
        if step is None:
            complete = [
                s for s, rec in steps.items()
                if rec["complete_ts"] is not None
            ]
            step = max(complete) if complete else None
        if step is None or int(step) not in steps:
            return {"ok": False, "error": f"no complete checkpoint for {run!r}"}
        src = steps[int(step)]
        if src["complete_ts"] is None:
            return {"ok": False, "error": f"{run!r} step {step} incomplete"}
        now = time.time()
        chunks: set[str] = set()
        completed = False
        for rank, r in src["ranks"].items():
            completed = self._ckpt_apply_commit(
                new_run, int(step), int(rank), src["world"],
                r["entries"], r["metrics"], now, r["parity"],
            ) or completed
            self._journal_append(
                "ckpt",
                "commit",
                {
                    "run": new_run,
                    "step": int(step),
                    "rank": int(rank),
                    "world": int(src["world"]),
                    "entries": list(r["entries"]),
                    "parity": list(r["parity"] or ()),
                    "metrics": dict(r["metrics"] or {}),
                    "ts": now,
                },
            )
            chunks |= manifest_chunks(r["entries"])
        if completed:
            self._ckpt_prune(new_run)
        return {
            "ok": True,
            "run": new_run,
            "step": int(step),
            "ranks": len(src["ranks"]),
            "chunks": len(chunks),
            # Content-addressed fork: the manifests are copied, the
            # chunks are not. Callers assert on this.
            "new_bytes": 0,
        }

    def _ckpt_referenced_chunks(self) -> set[str]:
        from ray_tpu.checkpoint.manifest import manifest_chunks, parity_chunks

        out: set[str] = set()
        for steps in self.checkpoints.values():
            for rec in steps.values():
                for r in rec["ranks"].values():
                    out |= manifest_chunks(r["entries"])
                    # Parity chunks are referenced too: GC'ing them
                    # would silently strip the erasure protection.
                    out |= parity_chunks(r.get("parity"))
        return out

    def _ckpt_parity_index(self) -> dict[str, dict]:
        """chunk → its parity-group record across every retained
        manifest (the repair loop's reconstruction lookup)."""
        from ray_tpu.checkpoint.manifest import parity_group_index

        out: dict[str, dict] = {}
        for steps in self.checkpoints.values():
            for rec in steps.values():
                for r in rec["ranks"].values():
                    for h, g in parity_group_index(r.get("parity")).items():
                        out.setdefault(h, g)
        return out

    async def _on_ckpt_locations_add(
        self, conn, addr: str, chunks: list[str]
    ):
        """Batched location report: a node that cached chunks it pulled
        (or reconstructed) during restore registers itself as a replica
        so peers can discover the copy and GC knows to collect it."""
        fresh = []
        for chunk in chunks:
            known = self.ckpt_locations.setdefault(chunk, set())
            if addr not in known:
                known.add(addr)
                fresh.append(chunk)
        if fresh:
            self._journal_append(
                "ckpt", "loc_many", {"addr": addr, "chunks": fresh}
            )
        return {"ok": True, "added": len(fresh)}

    def _ckpt_prune(self, run: str) -> None:
        """Retention: keep the newest CKPT_KEEP complete checkpoints per
        run; older manifests — and incomplete ones a newer complete
        checkpoint has obsoleted — prune, then their now-unreferenced
        chunks are collected off the holder nodes."""
        from ray_tpu._private import config

        steps = self.checkpoints.get(run, {})
        complete = sorted(
            s for s, rec in steps.items() if rec["complete_ts"] is not None
        )
        if not complete:
            return
        keep = set(complete[-max(1, int(config.get("CKPT_KEEP"))):])
        newest = complete[-1]
        victims = [
            s
            for s, rec in steps.items()
            if s not in keep
            and (rec["complete_ts"] is not None or s < newest)
        ]
        if not victims:
            return
        from ray_tpu.checkpoint.manifest import manifest_chunks

        from ray_tpu.checkpoint.manifest import parity_chunks

        victim_chunks: set[str] = set()
        for s in victims:
            rec = steps.pop(s)
            for r in rec["ranks"].values():
                victim_chunks |= manifest_chunks(r["entries"])
                victim_chunks |= parity_chunks(r.get("parity"))
            self._journal_append(
                "ckpt", "prune", {"run": run, "step": s}
            )
        garbage = victim_chunks - self._ckpt_referenced_chunks()
        if garbage:
            asyncio.ensure_future(self._ckpt_gc(garbage))

    async def _ckpt_gc(self, chunks: set[str]) -> None:
        """Delete unreferenced chunks from their holder nodes (best
        effort — a missed delete is shm garbage, not corruption)."""
        by_addr: dict[str, list[str]] = {}
        for chunk in chunks:
            holders = self.ckpt_locations.pop(chunk, set())
            for addr in holders:
                by_addr.setdefault(addr, []).append(chunk)
            if holders:
                self._journal_append(
                    "ckpt",
                    "loc_del",
                    {"chunk": chunk, "addrs": sorted(holders)},
                )
        conn_by_addr = {
            n["addr"]: self._node_conns.get(nid)
            for nid, n in self.nodes.items()
        }
        for addr, oids in by_addr.items():
            conn = conn_by_addr.get(addr)
            if conn is None:
                continue
            try:
                await conn.call("delete_objects", oids=oids)
            except Exception as e:  # noqa: BLE001 - node mid-death:
                logger.debug(        # GC never blocks on a dying holder
                    "checkpoint GC on %s failed: %r", addr, e
                )

    async def _on_ckpt_list(self, conn, run: str | None = None):
        from ray_tpu.checkpoint.manifest import entry_bytes, manifest_chunks

        out: dict[str, list] = {}
        for rname, steps in self.checkpoints.items():
            if run is not None and rname != run:
                continue
            rows = []
            for s in sorted(steps):
                rec = steps[s]
                chunks: set[str] = set()
                nbytes = 0
                n_groups = 0
                for r in rec["ranks"].values():
                    chunks |= manifest_chunks(r["entries"])
                    nbytes += sum(
                        entry_bytes(e) for e in r["entries"]
                    )
                    n_groups += len(r.get("parity") or ())
                replicas = [
                    len(self.ckpt_locations.get(h, ())) for h in chunks
                ]
                rows.append(
                    {
                        "step": s,
                        "world": rec["world"],
                        "ranks": sorted(rec["ranks"]),
                        "complete": rec["complete_ts"] is not None,
                        "ts": rec["complete_ts"],
                        "bytes": nbytes,
                        "chunks": len(chunks),
                        "min_replicas": min(replicas, default=0),
                        # Erasure durability at a glance: >0 parity
                        # groups means losses up to m per group decode
                        # instead of going to the repair/lost path.
                        "parity_groups": n_groups,
                    }
                )
            out[rname] = rows
        return {"ok": True, "runs": out}

    async def _on_ckpt_manifest(
        self, conn, run: str, step: int | None = None
    ):
        """Merged manifest of the newest complete checkpoint (or an
        exact complete step) plus current replica locations for every
        referenced chunk — everything restore needs in one call."""
        from ray_tpu.checkpoint.manifest import manifest_chunks

        steps = self.checkpoints.get(run, {})
        candidates = sorted(
            s
            for s, rec in steps.items()
            if rec["complete_ts"] is not None
            and (step is None or s == int(step))
        )
        if not candidates:
            return {
                "ok": False,
                "error": f"no complete checkpoint for run {run!r}"
                + (f" step {step}" if step is not None else ""),
            }
        s = candidates[-1]
        rec = steps[s]
        entries: dict[str, dict] = {}
        for rank in sorted(rec["ranks"]):
            for e in rec["ranks"][rank]["entries"]:
                cur = entries.get(e["key"])
                if cur is None:
                    entries[e["key"]] = {
                        "key": e["key"],
                        "shape": list(e["shape"]),
                        "dtype": e["dtype"],
                        "shards": list(e["shards"]),
                    }
                else:
                    # Process-sharded leaf: every rank holds disjoint
                    # windows of the same key; restore stitches them.
                    cur["shards"].extend(e["shards"])
        parity: list = []
        for rank in sorted(rec["ranks"]):
            parity.extend(rec["ranks"][rank].get("parity") or ())
        chunks = manifest_chunks(entries)
        from ray_tpu.checkpoint.manifest import parity_chunks

        chunks |= parity_chunks(parity)
        return {
            "ok": True,
            "run": run,
            "step": s,
            "world": rec["world"],
            "entries": entries,
            "parity": parity,
            "locations": {
                h: sorted(self.ckpt_locations.get(h, ()))
                for h in chunks
            },
        }

    async def _on_ckpt_verify(self, conn, run: str | None = None):
        """Probe every retained complete checkpoint's chunks on their
        recorded holders; report under-replicated and lost chunks (the
        `ray_tpu ckpt verify` backend)."""
        from ray_tpu._private import config
        from ray_tpu.checkpoint.manifest import manifest_chunks

        want = int(config.get("CKPT_REPLICATION"))
        alive = {n["addr"]: nid for nid, n in self.nodes.items()}
        conn_by_addr = {
            n["addr"]: self._node_conns.get(nid)
            for nid, n in self.nodes.items()
        }
        addr_slice = {
            n["addr"]: (n.get("labels") or {}).get("slice")
            for n in self.nodes.values()
        }
        reports = []
        for rname, steps in self.checkpoints.items():
            if run is not None and rname != run:
                continue
            for s, rec in sorted(steps.items()):
                if rec["complete_ts"] is None:
                    continue
                from ray_tpu.checkpoint.manifest import parity_chunks

                chunks: set[str] = set()
                groups: list[dict] = []
                for r in rec["ranks"].values():
                    chunks |= manifest_chunks(r["entries"])
                    groups.extend(r.get("parity") or ())
                    chunks |= parity_chunks(r.get("parity"))
                healthy_counts: dict[str, int] = {}
                healthy_holders: dict[str, list[str]] = {}
                for h in sorted(chunks):
                    n_ok = 0
                    holders: list[str] = []
                    for addr in self.ckpt_locations.get(h, ()):
                        node_conn = (
                            conn_by_addr.get(addr)
                            if addr in alive
                            else None
                        )
                        if node_conn is None:
                            continue
                        try:
                            meta = await node_conn.call(
                                "get_object_meta", oid_hex=h
                            )
                        except Exception as e:  # noqa: BLE001
                            logger.debug(  # dead holder = missing replica
                                "verify probe %s on %s: %r", h, addr, e
                            )
                            continue
                        if meta.get("ok"):
                            n_ok += 1
                            holders.append(addr)
                    healthy_counts[h] = n_ok
                    healthy_holders[h] = holders
                # Replica spread: two replicas of a chunk sharing a
                # slice are one preemption away from being one replica
                # — flag them so `ray_tpu ckpt verify` warns before the
                # slice goes away, not after.
                colocated = []
                for h, holders in healthy_holders.items():
                    by_slice: dict[str, int] = {}
                    for addr in holders:
                        sl = addr_slice.get(addr)
                        if sl:
                            by_slice[sl] = by_slice.get(sl, 0) + 1
                    if any(v >= 2 for v in by_slice.values()):
                        colocated.append(h)
                # Erasure-group health: a group is intact while every
                # member has a healthy replica, degraded (but fully
                # reconstructable) while ≤m members are down, lost once
                # more than m are — degraded is the repair loop's work
                # queue, lost is the alarm.
                g_intact = g_degraded = g_lost = 0
                reconstructable: set[str] = set()
                for g in groups:
                    members = list(g.get("data", ())) + list(
                        g.get("parity", ())
                    )
                    m_tol = len(g.get("parity", ()))
                    down = [
                        h
                        for h in members
                        if healthy_counts.get(h, 0) == 0
                    ]
                    if not down:
                        g_intact += 1
                    elif len(down) <= m_tol:
                        g_degraded += 1
                        reconstructable.update(down)
                    else:
                        g_lost += 1
                target = min(want, max(1, len(alive)))
                reports.append(
                    {
                        "run": rname,
                        "step": s,
                        "chunks": len(chunks),
                        "replication_target": target,
                        "healthy": sum(
                            1
                            for v in healthy_counts.values()
                            if v >= target
                        ),
                        "under_replicated": sorted(
                            h
                            for h, v in healthy_counts.items()
                            if 0 < v < target
                        ),
                        "lost": sorted(
                            h
                            for h, v in healthy_counts.items()
                            if v == 0
                        ),
                        "reconstructable": sorted(reconstructable),
                        "groups": {
                            "intact": g_intact,
                            "degraded": g_degraded,
                            "lost": g_lost,
                        },
                        "colocated": sorted(colocated),
                    }
                )
        return {"ok": True, "checkpoints": reports}

    # ------------------------------------------------ checkpoint repair
    def _schedule_ckpt_repair(self) -> None:
        """Kick the repair pass (rate-limited, single-flight). Called
        from the health loop tick and eagerly on node death/drain."""
        from ray_tpu._private import config

        if self._ckpt_repairing or not self.ckpt_locations or not self.nodes:
            return
        if (
            time.monotonic() - self._ckpt_last_repair
            < config.get("CKPT_REPAIR_INTERVAL_S")
        ):
            return
        self._ckpt_repairing = True
        asyncio.ensure_future(self._ckpt_repair_bg())

    async def _ckpt_repair_bg(self) -> None:
        try:
            await self._ckpt_repair()
        except Exception as e:  # noqa: BLE001 - repair must keep ticking
            logger.warning("checkpoint repair pass failed: %r", e)
        finally:
            self._ckpt_last_repair = time.monotonic()
            self._ckpt_repairing = False

    async def _ckpt_repair(self) -> None:
        """Re-replicate under-replicated checkpoint chunks.

        A holder is *live* while its node is registered and *healthy*
        while additionally not DRAINING — so a drain notice immediately
        makes chunks whose only replicas live on the draining node
        eligible for evacuation, before the node dies. Dead holders are
        only forgotten once a chunk is healthy again (never drop the
        last record of where data might still be).

        Target choice is SLICE-AWARE: a replica on the same slice as an
        existing holder dies with it (whole-slice preemption), so
        candidates on slices that do not already hold the chunk come
        first — whole-slice loss then never destroys every copy."""
        from ray_tpu._private import config

        want = int(config.get("CKPT_REPLICATION"))
        alive = {n["addr"]: nid for nid, n in self.nodes.items()}
        draining_addrs = {
            self.nodes[nid]["addr"]
            for nid in self.draining
            if nid in self.nodes
        }
        addr_slice = {
            n["addr"]: (n.get("labels") or {}).get("slice")
            for n in self.nodes.values()
        }
        healthy_addrs = set(alive) - draining_addrs
        if not healthy_addrs:
            return
        referenced = self._ckpt_referenced_chunks()
        # (source, target) → chunks: one batched prefetch per pair.
        plan: dict[tuple[str, str], list[str]] = {}
        # Chunks with ZERO live replicas: unrecoverable by copying, but
        # an erasure group with ≥k surviving members can re-encode them.
        zero_replica: list[str] = []
        for chunk in referenced:
            locs = self.ckpt_locations.get(chunk)
            if not locs:
                zero_replica.append(chunk)
                continue
            live = locs & set(alive)
            healthy = live - draining_addrs
            target_n = min(want, len(healthy_addrs))
            if len(healthy) >= target_n:
                dead = locs - set(alive)
                if dead:
                    locs.difference_update(dead)
                    self._journal_append(
                        "ckpt",
                        "loc_del",
                        {"chunk": chunk, "addrs": sorted(dead)},
                    )
                continue
            sources = sorted(healthy) or sorted(live)
            if not sources:
                # Every replica gone: reconstruction is the only move.
                zero_replica.append(chunk)
                continue
            held_slices = {
                addr_slice.get(a) for a in live if addr_slice.get(a)
            }
            candidates = sorted(
                healthy_addrs - live,
                key=lambda a: (
                    addr_slice.get(a) is not None
                    and addr_slice[a] in held_slices,
                    a,
                ),
            )
            for tgt in candidates[: target_n - len(healthy)]:
                plan.setdefault((sources[0], tgt), []).append(chunk)
        for (src, tgt), chunks in plan.items():
            node_conn = self._node_conns.get(alive.get(tgt, ""))
            if node_conn is None:
                continue
            try:
                reply = await node_conn.call(
                    "prefetch_objects", oids=chunks, owner_addr=src
                )
            except Exception as e:  # noqa: BLE001 - target died
                logger.debug(        # mid-repair: next tick replans
                    "repair prefetch %s→%s failed: %r", src, tgt, e
                )
                continue
            results = reply.get("results", {})
            for chunk in chunks:
                if results.get(chunk):
                    self.ckpt_locations.setdefault(chunk, set()).add(tgt)
                    self._journal_append(
                        "ckpt", "loc", {"chunk": chunk, "addrs": [tgt]}
                    )
        if zero_replica:
            await self._ckpt_reconstruct_lost(
                zero_replica, alive, healthy_addrs
            )

    async def _ckpt_reconstruct_lost(
        self, chunks: list[str], alive: dict, healthy_addrs: set[str]
    ) -> None:
        """Erasure-aware repair: a chunk with zero live replicas is
        re-ENCODED on a healthy node from its parity group's survivors
        (k member pulls + a small GF solve) instead of being written
        off — the whole point of paying the m/k parity bytes."""
        group_of = self._ckpt_parity_index()
        for chunk in chunks:
            g = group_of.get(chunk)
            if g is None:
                continue  # no parity group: stays lost until a holder returns
            members = list(g.get("data", ())) + list(g.get("parity", ()))
            k = len(g.get("data", ()))
            rows = []
            for idx, mh in enumerate(members):
                if mh == chunk:
                    continue
                holders = sorted(
                    a
                    for a in self.ckpt_locations.get(mh, ())
                    if a in alive
                )
                if holders:
                    rows.append(
                        {"member": idx, "hash": mh, "addrs": holders}
                    )
            if len(rows) < k:
                logger.warning(
                    "ckpt chunk %s lost: only %d/%d group members "
                    "survive", chunk[:12], len(rows), k,
                )
                continue
            # Run the decode where the most survivors already live:
            # fewest cross-node member pulls.
            held: dict[str, int] = {}
            for r in rows:
                for a in r["addrs"]:
                    if a in healthy_addrs:
                        held[a] = held.get(a, 0) + 1
            tgt = max(
                sorted(healthy_addrs), key=lambda a: held.get(a, 0)
            )
            node_conn = self._node_conns.get(alive.get(tgt, ""))
            if node_conn is None:
                continue
            try:
                reply = await node_conn.call(
                    "ckpt_reconstruct",
                    chunk=chunk,
                    k=k,
                    m=len(g.get("parity", ())),
                    member=members.index(chunk),
                    rows=rows[: k + 2],
                    lens=g.get("lens"),
                )
            except Exception as e:  # noqa: BLE001 - target died
                logger.debug(        # mid-repair: next tick replans
                    "reconstruct %s on %s failed: %r", chunk[:12], tgt, e
                )
                continue
            if reply.get("ok"):
                self.ckpt_locations.setdefault(chunk, set()).add(tgt)
                self._journal_append(
                    "ckpt", "loc", {"chunk": chunk, "addrs": [tgt]}
                )
                logger.info(
                    "reconstructed lost ckpt chunk %s on %s from its "
                    "parity group", chunk[:12], tgt,
                )

    async def _on_pick_node(
        self,
        conn,
        resources: dict | None = None,
        requester: str | None = None,
        labels_hard: dict | None = None,
        labels_soft: dict | None = None,
    ):
        """Cluster-level placement: pick a feasible node for a lease.

        Reference analogue: the hybrid scheduling policy's feasibility +
        availability scoring (reference:
        src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.h:25)
        plus the node-label policy (node_label_scheduling_policy);
        centralized here (GCS-style) rather than spilled raylet-to-raylet.
        """
        from ray_tpu.util.scheduling_strategies import labels_match

        resources = resources or {}
        if not labels_hard and not labels_soft:
            # Hot path (actor/PG storms are label-free): one vectorized
            # scan over the maintained columns instead of per-node dict
            # work — the O(picks x nodes) Python constant was what bent
            # the 50→100-node curve sublinear (PROFILE_r05.md).
            best = self._pick_node_fast(resources)
            return self._pick_node_reply(best, resources, requester)
        # Hybrid policy (reference: hybrid_scheduling_policy.h:25-50):
        # skip infeasible, prefer nodes that can run NOW, rank by
        # post-placement utilization, then pick RANDOMLY among the top-k
        # so concurrent drivers don't herd onto one node.
        candidates: list[tuple[tuple, str]] = []
        for nid, node in self.nodes.items():
            if nid in self.draining:
                continue  # drained nodes take no new leases
            avail = node["available"]
            total = node["resources"]
            if any(total.get(k, 0) < v for k, v in resources.items()):
                continue  # infeasible
            if labels_hard and not labels_match(
                node.get("labels", {}), labels_hard
            ):
                continue
            soft_hits = (
                sum(
                    1
                    for k, want in (labels_soft or {}).items()
                    if labels_match(node.get("labels", {}), {k: want})
                )
                if labels_soft
                else 0
            )
            available_now = all(
                avail.get(k, 0) >= v for k, v in resources.items()
            )
            # Utilization AFTER placing this request: max over the
            # requested resource kinds (the reference's critical
            # resource), 0 when nothing specific is requested.
            util = max(
                (
                    (total[k] - avail.get(k, 0) + v) / total[k]
                    for k, v in resources.items()
                    if total.get(k, 0) > 0
                ),
                default=0.0,
            )
            candidates.append(
                ((not available_now, -soft_hits, util), nid)
            )
        best = None
        if candidates:
            import random

            candidates.sort(key=lambda c: c[0])
            top_k = candidates[: min(3, len(candidates))]
            # Only mix nodes of the SAME (availability, soft-label)
            # class: never pick a busy node while an idle one is in the
            # slice, and never trade a soft-label match for spread.
            top_k = [
                c for c in top_k if c[0][:2] == top_k[0][0][:2]
            ]
            best = random.choice(top_k)[1]
        return self._pick_node_reply(best, resources, requester)

    def _sched_columns(self) -> dict:
        """(Re)build the vectorized scheduling columns from self.nodes:
        a stable node list plus per-resource-kind total/available numpy
        arrays and an `eligible` mask. Only genuine membership growth
        (new node, new resource kind) invalidates; _on_sync writes
        values in place and drain/undrain/death flip eligibility bits
        (_sched_set_eligible) — O(1) per churn event, where the old
        rebuild-on-every-change made a mass-drain storm interleaved
        with picks O(nodes²)."""
        cols = self._sched_cols
        if cols is None:
            import numpy as np

            nids = list(self.nodes)
            kinds: set[str] = set()
            for nid in nids:
                kinds.update(self.nodes[nid]["resources"])
                kinds.update(self.nodes[nid]["available"])
            cols = self._sched_cols = {
                "nids": nids,
                "idx": {nid: i for i, nid in enumerate(nids)},
                "eligible": np.array(
                    [nid not in self.draining for nid in nids], bool
                ),
                "dead": 0,
                "total": {
                    k: np.array(
                        [
                            float(self.nodes[nid]["resources"].get(k, 0))
                            for nid in nids
                        ]
                    )
                    for k in kinds
                },
                "avail": {
                    k: np.array(
                        [
                            float(self.nodes[nid]["available"].get(k, 0))
                            for nid in nids
                        ]
                    )
                    for k in kinds
                },
            }
        return cols

    def _sched_set_eligible(self, node_id: str, eligible: bool) -> None:
        """O(1) schedulability flip on the maintained columns. Dead
        rows (removed nodes) stay masked-out in place; once they are
        the majority the next pick rebuilds compactly."""
        cols = self._sched_cols
        if cols is None:
            return
        i = cols["idx"].get(node_id)
        if i is None:
            if eligible:
                self._sched_cols = None  # unknown node joining the pool
            return
        cols["eligible"][i] = eligible

    def _sched_drop_node(self, node_id: str) -> None:
        """Mask a removed node out of the columns (O(1)); rebuild only
        when dead rows dominate."""
        cols = self._sched_cols
        if cols is None:
            return
        i = cols["idx"].get(node_id)
        if i is None:
            return
        cols["eligible"][i] = False
        cols["dead"] += 1
        if cols["dead"] * 2 > len(cols["nids"]):
            self._sched_cols = None

    def _pick_node_fast(self, resources: dict) -> str | None:
        """Label-free hybrid pick over the vectorized columns — same
        ranking as the general path (feasible → available-now class →
        post-placement utilization → random among the top-3 of the best
        class), with the per-node work done by numpy."""
        import random

        import numpy as np

        cols = self._sched_columns()
        n = len(cols["nids"])
        if n == 0:
            return None
        feasible = cols["eligible"].copy()
        avail_now = np.ones(n, bool)
        util = np.zeros(n)
        for k, v in resources.items():
            tot = cols["total"].get(k)
            if tot is None:
                if v > 0:
                    return None  # no node has this kind at all
                # Zero demand for an unknown kind constrains nothing
                # (matches the general path: total.get(k, 0) < 0 is
                # never true) — e.g. .options(num_tpus=0).
                continue
            av = cols["avail"][k]
            if v > 0:
                feasible &= tot >= v
                avail_now &= av >= v
            pos = tot > 0
            u = np.zeros(n)
            u[pos] = (tot[pos] - av[pos] + v) / tot[pos]
            util = np.maximum(util, u)
        idx = np.nonzero(feasible)[0]
        if idx.size == 0:
            return None
        # Lexicographic (not available_now, util) folded into one key:
        # util is bounded (~1 + v/min_total), far under the 1e9 class
        # separator.
        comp = (~avail_now[idx]).astype(np.float64) * 1e9 + util[idx]
        k3 = min(3, idx.size)
        part = np.argpartition(comp, k3 - 1)[:k3]
        top = idx[part[np.argsort(comp[part], kind="stable")]]
        best_class = avail_now[top[0]]
        same = [int(t) for t in top if avail_now[t] == best_class]
        return cols["nids"][random.choice(same)]

    def _pick_node_reply(
        self, best: str | None, resources: dict, requester: str | None
    ) -> dict:
        if best is None:
            # Record cluster-wide unschedulable demand: the autoscaler's
            # strongest scale-up signal (reference: pending demand in
            # GetClusterResourceState feeding v2/scheduler.py).
            if requester is not None:
                self.unschedulable[requester] = (
                    dict(resources), time.monotonic()
                )
                if len(self.unschedulable) > 10000:
                    self._expire_unschedulable()
            return {"ok": False, "error": "no feasible node"}
        if requester is not None:
            self.unschedulable.pop(requester, None)
        return {"ok": True, "node_id": best, "addr": self.nodes[best]["addr"]}

    def _expire_unschedulable(self, ttl: float = 5.0):
        now = time.monotonic()
        for key, (_r, ts) in list(self.unschedulable.items()):
            if now - ts > ttl:
                del self.unschedulable[key]

    # ------------------------------------------------------------- kv
    async def _on_kv_put(self, conn, key: str, value: bytes, overwrite=True):
        # overwrite=False callers MUST pass retry=False through their
        # ReconnectingClient: a blind re-send that observes its own
        # first write would report {ok: False, exists: True} to the
        # writer that actually won the race.
        if not overwrite and key in self.kv:
            return {"ok": False, "exists": True}
        self.kv[key] = value
        self._journal_append("kv", "put", {"key": key, "value": value})
        return {"ok": True}

    async def _on_kv_get(self, conn, key: str):
        return {"ok": key in self.kv, "value": self.kv.get(key)}

    async def _on_kv_del(self, conn, key: str):
        existed = self.kv.pop(key, None) is not None
        if existed:
            self._journal_append("kv", "del", {"key": key})
        return {"ok": existed}

    async def _on_kv_keys(self, conn, prefix: str = ""):
        return {"keys": [k for k in self.kv if k.startswith(prefix)]}

    # ----------------------------------------------------------- actors
    async def _on_register_actor(
        self,
        conn,
        actor_id: str,
        name: str | None,
        class_name: str,
        addr: str,
        node_id: str,
        detached: bool = False,
        restart_spec: dict | None = None,
    ):
        if name:
            existing = self.named_actors.get(name)
            if existing and self.actors[existing]["state"] != "DEAD":
                return {"ok": False, "error": f"actor name {name!r} taken"}
            self.named_actors[name] = actor_id
        self.actors[actor_id] = {
            "name": name,
            "state": "ALIVE",
            "addr": addr,
            "node_id": node_id,
            "class_name": class_name,
            "detached": detached,
            "restart_spec": restart_spec,
            "restarts_used": 0,
        }
        self._journal_append(
            "actor",
            "put",
            {
                "actor_id": actor_id,
                "fields": self._durable_actor(self.actors[actor_id]),
            },
        )
        self.publish("actor", {"event": "alive", "actor_id": actor_id})
        return {"ok": True}

    async def _on_restart_actor(self, conn, actor_id: str, failed_addr: str):
        """Caller-reported actor death → restart if budget remains
        (reference: GcsActorManager::RestartActor on worker-failure
        notice; callers resubmit per max_task_retries). Idempotent: all
        concurrent reporters get the single restart's outcome."""
        actor = self.actors.get(actor_id)
        if actor is None:
            return {"ok": False, "state": "DEAD"}
        from ray_tpu._private.sanitize import maybe_async_lock

        lock = actor.setdefault(
            "_restart_lock",
            maybe_async_lock(f"head.actor_restart.{actor_id}"))
        async with lock:
            if actor["state"] == "ALIVE" and actor["addr"] != failed_addr:
                # Another reporter already drove the restart.
                return {"ok": True, "state": "ALIVE", "addr": actor["addr"]}
            if actor["state"] == "DEAD":
                return {"ok": False, "state": "DEAD"}
            spec = actor.get("restart_spec") or {}
            budget = spec.get("max_restarts", 0)
            if budget != -1 and actor["restarts_used"] >= budget:
                actor["state"] = "DEAD"
                self._journal_append(
                    "actor",
                    "update",
                    {"actor_id": actor_id, "fields": {"state": "DEAD"}},
                )
                self.publish("actor", {"event": "dead", "actor_id": actor_id})
                return {"ok": False, "state": "DEAD"}
            actor["restarts_used"] += 1
            actor["state"] = "RESTARTING"
            self.publish(
                "actor", {"event": "restarting", "actor_id": actor_id}
            )
            try:
                addr = await self._recreate_actor(actor_id, actor, spec)
            # tpulint: allow(broad-except reason=not swallowed - the actor is journaled DEAD with the error published to watchers below)
            except Exception as e:
                actor["state"] = "DEAD"
                self._journal_append(
                    "actor",
                    "update",
                    {"actor_id": actor_id, "fields": {"state": "DEAD"}},
                )
                self.publish("actor", {"event": "dead", "actor_id": actor_id})
                return {"ok": False, "state": "DEAD", "error": repr(e)}
            if actor["state"] == "DEAD":
                # A kill landed while the restart was in flight: the kill
                # wins — tear down the instance we just created.
                await self._kill_worker_quietly(addr)
                return {"ok": False, "state": "DEAD"}
            actor.update(state="ALIVE", addr=addr)
            self._journal_append(
                "actor",
                "update",
                {
                    "actor_id": actor_id,
                    "fields": {
                        "state": "ALIVE",
                        "addr": addr,
                        "node_id": actor["node_id"],
                        "restarts_used": actor["restarts_used"],
                    },
                },
            )
            self.publish(
                "actor",
                {"event": "alive", "actor_id": actor_id, "addr": addr},
            )
            return {"ok": True, "state": "ALIVE", "addr": addr}

    async def _kill_worker_quietly(self, addr: str):
        try:
            conn = await rpc.connect(addr)
            try:
                await conn.call("exit_worker")
            finally:
                await conn.close()
        # tpulint: allow(broad-except reason=quiet kill of a superseded worker that may already be gone; success is not required, only attempted cleanup)
        except Exception:
            pass

    def _spawn_restart(self, actor_id: str, failed_addr: str) -> None:
        """Fire-and-forget restart attempt (node-death sweep); tracked so
        the task isn't GC'd. _on_restart_actor handles budget/DEAD."""
        task = asyncio.ensure_future(
            self._on_restart_actor(None, actor_id, failed_addr)
        )
        self._bg_restarts = getattr(self, "_bg_restarts", set())
        self._bg_restarts.add(task)
        task.add_done_callback(self._bg_restarts.discard)

    async def _recreate_actor(self, actor_id: str, actor: dict, spec: dict):
        """Lease a fresh worker and re-run the actor's constructor."""
        placement = spec.get("placement")
        if placement is not None:
            # PG-placed actor: restart on its reserved bundle so
            # co-location (and the bundle's accounting) stays intact.
            pg_id, index = placement[1], placement[2]
            pg = self.placement_groups.get(pg_id)
            if pg is None:
                raise rpc.RpcError(
                    f"placement group {pg_id} gone; cannot restart"
                )
            node_id = pg["nodes"][index]
            node_conn = self._node_conns.get(node_id)
            if node_conn is None:
                raise rpc.RpcError("bundle node is gone; cannot restart")
            lease = await node_conn.call(
                "lease_worker",
                resources=dict(spec["resources"]),
                actor=True,
                bundle=(pg_id, index),
                runtime_env=spec.get("runtime_env"),
            )
        else:
            sched = spec.get("scheduling") or {}
            affinity = sched.get("node_id")
            if affinity is not None and affinity in self.nodes:
                node_id = affinity
            elif affinity is not None and not sched.get("soft"):
                # Hard affinity to a node that no longer exists: the
                # actor must not silently move (core_worker would have
                # refused the first placement the same way).
                raise rpc.RpcError(
                    f"hard node affinity: node {affinity[:12]}… is gone"
                )
            else:
                pick = await self._on_pick_node(
                    None,
                    resources=spec["resources"],
                    labels_hard=sched.get("labels_hard"),
                    labels_soft=sched.get("labels_soft"),
                )
                if not pick.get("ok"):
                    raise rpc.RpcError(pick.get("error", "no feasible node"))
                node_id = pick["node_id"]
            node_conn = self._node_conns[node_id]
            lease = await node_conn.call(
                "lease_worker",
                resources=dict(spec["resources"]),
                actor=True,
                runtime_env=spec.get("runtime_env"),
            )
        if not lease.get("ok"):
            raise rpc.RpcError(lease.get("error", "restart lease failed"))
        try:
            worker_conn = await rpc.connect(lease["addr"])
            try:
                create = await worker_conn.call(
                    "create_actor",
                    actor_id=actor_id,
                    fn_id=spec["fn_id"],
                    args=spec["args"],
                    max_concurrency=spec.get("max_concurrency"),
                )
            finally:
                await worker_conn.close()
            if create.get("status") == "error":
                raise rpc.RpcError("actor constructor failed on restart")
        except Exception:
            # Give the lease (and its worker) back: a failed restart must
            # not strand cluster capacity.
            try:
                await node_conn.call(
                    "return_lease", lease_id=lease["lease_id"]
                )
            except rpc.RpcError:
                pass
            raise
        actor["node_id"] = node_id
        return lease["addr"]

    async def _on_update_actor(self, conn, actor_id: str, state: str):
        actor = self.actors.get(actor_id)
        if actor is None:
            return {"ok": False}
        actor["state"] = state
        self._journal_append(
            "actor", "update", {"actor_id": actor_id, "fields": {"state": state}}
        )
        self.publish("actor", {"event": state.lower(), "actor_id": actor_id})
        return {"ok": True}

    async def _on_get_actor(
        self, conn, name: str | None = None, actor_id: str | None = None
    ):
        if name is not None:
            actor_id = self.named_actors.get(name)
        if actor_id is None or actor_id not in self.actors:
            return {"ok": False, "error": "actor not found"}
        if self.actors[actor_id]["state"] == "DEAD":
            # A killed detached actor must not resolve by name: the
            # get-or-create pattern (serve's controller/proxy bootstrap)
            # would otherwise revive a handle to a corpse right after
            # shutdown (reference: ray.get_actor raises for dead
            # actors).
            return {"ok": False, "error": "actor not found (dead)"}
        return {
            "ok": True,
            "actor_id": actor_id,
            **self._public_actor(self.actors[actor_id]),
        }

    @staticmethod
    def _public_actor(actor: dict) -> dict:
        """Strip non-serializable / internal fields (restart lock, spec)."""
        return {
            k: v
            for k, v in actor.items()
            if k not in ("_restart_lock", "restart_spec")
        }

    async def _on_list_actors(self, conn):
        return {
            "actors": {
                aid: self._public_actor(a) for aid, a in self.actors.items()
            }
        }

    # ----------------------------------------------------------- pubsub
    async def _on_subscribe(self, conn, channel: str):
        self.subs.setdefault(channel, set()).add(conn)
        conn.state.setdefault("channels", []).append(channel)
        return {"ok": True}

    async def _on_publish(self, conn, channel: str, msg):
        # Worker-death reports from node reap loops double as collective
        # abort triggers: a SIGKILLed member on a LIVE node must poison
        # its groups without waiting for any op deadline.
        if (
            channel == "worker"
            and isinstance(msg, dict)
            and msg.get("event") == "died"
        ):
            self._collective_member_died(worker_id=msg.get("worker_id"))
        self.publish(channel, msg)
        return {"ok": True}

    # ------------------------------------------------ collective groups
    async def _on_collective_register(
        self,
        conn,
        group: str,
        rank: int,
        epoch: int = 0,
        addr: str | None = None,
        node_addr: str | None = None,
        worker_id: str | None = None,
    ):
        """Membership registration (reference: the NCCL group's named
        rendezvous actor, here head-owned so death detection can cross-
        reference the node table). A higher epoch — a reform — replaces
        the previous incarnation wholesale."""
        rec = self.collective_members.get(group)
        if rec is None or epoch > rec["epoch"]:
            rec = self.collective_members[group] = {
                "epoch": int(epoch),
                "members": {},
            }
        if epoch < rec["epoch"]:
            return {"ok": False, "stale": True}
        rec["members"][int(rank)] = {
            "addr": addr,
            "node_addr": node_addr,
            "worker_id": worker_id,
            "dead": False,
        }
        return {"ok": True}

    async def _on_collective_deregister(
        self, conn, group: str, epoch: int | None = None, rank=None
    ):
        rec = self.collective_members.get(group)
        if rec is None:
            return {"ok": False}
        if epoch is not None and rec["epoch"] != int(epoch):
            return {"ok": False, "stale": True}
        if rank is None:
            del self.collective_members[group]
        else:
            rec["members"].pop(int(rank), None)
            if not rec["members"]:
                del self.collective_members[group]
        return {"ok": True}

    def _collective_member_died(
        self,
        node_addr: str | None = None,
        worker_id: str | None = None,
    ):
        """Cross-reference a dead node/worker against every collective
        group and fan the member deaths out to the survivors."""
        for group, rec in self.collective_members.items():
            dead = []
            for r, m in rec["members"].items():
                if m.get("dead"):
                    continue
                if (node_addr is not None and m.get("node_addr") == node_addr) or (
                    worker_id is not None
                    and m.get("worker_id") == worker_id
                ):
                    m["dead"] = True
                    dead.append(r)
            if dead:
                self.publish(
                    "collective",
                    {
                        "event": "member_dead",
                        "group": group,
                        "epoch": rec["epoch"],
                        "ranks": sorted(dead),
                    },
                )

    async def _on_collective_straggler_stats(self, conn):
        """Straggler telemetry aggregated to NODES: sum the hub-reported
        collective_straggler_total series across worker snapshots and
        resolve each (group, rank) to its member's node through the
        membership table. This is the autoscaler's chronic-straggler
        signal — a node that is repeatedly the slowest (or missing)
        contributor is a replacement candidate before it becomes a
        timeout."""
        from ray_tpu.util.metrics import parse_tag_str

        per_pair: dict[tuple[str, str], float] = {}
        for rec in self.metrics.values():
            m = rec["snap"].get("collective_straggler_total")
            if not m:
                continue
            for tag_str, val in m.get("series", {}).items():
                tags = parse_tag_str(tag_str)
                key = (tags.get("group", ""), tags.get("rank", ""))
                per_pair[key] = per_pair.get(key, 0.0) + float(val)
        nodes: dict[str, float] = {}
        groups: dict[str, dict] = {}
        addr_to_nid = {n["addr"]: nid for nid, n in self.nodes.items()}
        for (group, rank), val in per_pair.items():
            groups.setdefault(group, {})[rank] = val
            members = self.collective_members.get(group, {}).get(
                "members", {}
            )
            try:
                node_addr = members.get(int(rank), {}).get("node_addr")
            except (TypeError, ValueError):
                node_addr = None
            nid = addr_to_nid.get(node_addr) if node_addr else None
            if nid is not None:
                nodes[nid] = nodes.get(nid, 0.0) + val
        # Hub-escalated partial skips count too — they arrive ahead of
        # the metric-snapshot flush latency.
        for nid, val in self.chronic_skip_reports.items():
            nodes[nid] = max(nodes.get(nid, 0.0), float(val))
        return {"ok": True, "nodes": nodes, "groups": groups}

    async def _on_collective_straggler_report(
        self,
        conn,
        group: str,
        rank: int,
        skips: int = 0,
        window_s: float = 0.0,
    ):
        """A hub escalated a chronic partial-collective straggler: its
        skip rate crossed the sliding-window threshold. Resolve the rank
        to its node and — unless COLLECTIVE_SKIP_DRAIN is off — put the
        node on the same drain-and-replace path the autoscaler uses for
        chronic stragglers: DRAINING excludes it from new placements,
        the notice fans out, and the autoscaler provisions a
        replacement. A slow host becomes a bounded throughput dip that
        self-heals instead of a stall-then-collapse."""
        from ray_tpu._private import config

        rec = self.collective_members.get(group)
        members = (rec or {}).get("members", {})
        node_addr = members.get(int(rank), {}).get("node_addr")
        nid = next(
            (
                i
                for i, n in self.nodes.items()
                if node_addr and n["addr"] == node_addr
            ),
            None,
        )
        if nid is None:
            return {"ok": False, "error": f"cannot resolve rank {rank} "
                                          f"of group {group!r} to a node"}
        self.chronic_skip_reports[nid] = max(
            self.chronic_skip_reports.get(nid, 0.0), float(skips)
        )
        logger.warning(
            "node %s (rank %d of collective group %r) was skipped by %d "
            "partial collectives in %.0fs: chronic straggler",
            nid[:12], int(rank), group, int(skips), window_s,
        )
        drained = False
        if config.get("COLLECTIVE_SKIP_DRAIN") and nid not in self.draining:
            reply = await self._on_drain_node(
                conn,
                node_id=nid,
                reason=(
                    f"chronic straggler: {int(skips)} partial-collective "
                    f"skips in {window_s:.0f}s"
                ),
            )
            drained = bool(reply.get("ok"))
        return {"ok": True, "node_id": nid, "drained": drained}

    async def _on_collective_probe(
        self, conn, group: str, ranks=None
    ):
        """Active member health check, fired by a group when an op
        deadline expires (reference: gcs_health_check_manager.h:45 active
        probes vs passive heartbeats). Confirms whether the silent ranks
        are actually dead — a dead NODE is removed from the cluster now
        (instead of aging out of HEALTH_TIMEOUT_S), a dead WORKER on a
        live node fans out member death; a merely-slow member is left
        alone."""
        rec = self.collective_members.get(group)
        if rec is None:
            return {"ok": False, "error": f"unknown group {group!r}"}
        members = rec["members"]
        targets = (
            [int(r) for r in ranks] if ranks is not None else list(members)
        )
        confirmed: list[int] = []
        for r in targets:
            m = members.get(r)
            if m is None or m.get("dead"):
                continue
            node_addr = m.get("node_addr")
            nid = next(
                (
                    i
                    for i, n in self.nodes.items()
                    if n["addr"] == node_addr
                ),
                None,
            )
            if node_addr and nid is None:
                # Node already gone from the table: the member died with it.
                self._collective_member_died(node_addr=node_addr)
                confirmed.append(r)
                continue
            node_conn = self._node_conns.get(nid) if nid else None
            if node_conn is not None:
                try:
                    reply = await node_conn.call("list_workers", timeout=2.0)
                # tpulint: allow(broad-except reason=any probe failure means the node is unreachable - acted on by removing the node, not swallowed)
                except Exception:
                    await self._remove_node(nid)
                    confirmed.append(r)
                    continue
                wid = m.get("worker_id")
                if wid is not None and wid not in {
                    w["worker_id"] for w in reply.get("workers", [])
                }:
                    self._collective_member_died(worker_id=wid)
                    confirmed.append(r)
        return {"ok": True, "dead_ranks": sorted(confirmed)}

    # -------------------------------------------------- placement groups
    async def _on_create_placement_group(
        self, conn, pg_id: str, bundles: list, strategy: str = "PACK"
    ):
        """Gang-reserve resource bundles (reference:
        GcsPlacementGroupManager gcs_placement_group_manager.h:50 with the
        2PC prepare/commit scheduler gcs_placement_group_scheduler.h:115;
        strategies python/ray/util/placement_group.py).

        The plan comes from the head's resource VIEW, which can lag a
        just-finished scheduling burst (sync is push-on-change); a node
        may therefore refuse its reservation at prepare time. Like the
        reference's scheduler, the refusal reschedules the group around
        the refusing node instead of failing the creation.
        """
        excluded: set[str] = set()
        last_error = "no nodes"
        for _attempt in range(4):
            plan = self._plan_placement(bundles, strategy, excluded)
            if not plan.get("ok"):
                return plan
            placed = plan["placed"]
            committed = []
            failing: str | None = None
            try:
                for (nid, i), bundle in zip(placed, bundles):
                    # Any failure against THIS node — an explicit
                    # refusal (stale view), a dropped conn, or a node
                    # that died after planning — reschedules around it;
                    # other nodes may still fit the group.
                    failing = nid
                    conn_ = self._node_conns.get(nid)
                    if conn_ is None:
                        raise rpc.RpcError(f"node {nid} has no conn")
                    reply = await conn_.call(
                        "reserve_bundle",
                        pg_id=pg_id,
                        index=i,
                        resources=bundle,
                    )
                    if not reply.get("ok"):
                        raise rpc.RpcError(
                            reply.get("error", "reserve failed")
                        )
                    failing = None
                    committed.append((nid, i))
            # tpulint: allow(broad-except reason=not swallowed - prepares are rolled back and the error is returned or retried with the failing node excluded)
            except Exception as e:
                for nid, i in committed:
                    # A node that died between reserve and rollback must
                    # not abort freeing the remaining nodes' bundles
                    # (its own reservations die with it), so: tolerate a
                    # missing conn and catch broadly — any per-node
                    # failure here is that node's problem, not the
                    # rollback's.
                    conn_ = self._node_conns.get(nid)
                    if conn_ is None:
                        continue
                    try:
                        await conn_.call(
                            "free_bundle", pg_id=pg_id, index=i
                        )
                    # tpulint: allow(broad-except reason=a node that died between reserve and rollback frees its own bundles by dying; the loop must keep freeing the others)
                    except Exception:
                        pass
                last_error = str(e)
                if failing is None:
                    return {"ok": False, "error": last_error}
                excluded.add(failing)
                continue
            self.placement_groups[pg_id] = {
                "bundles": bundles,
                "strategy": strategy,
                "nodes": [nid for nid, _ in placed],
            }
            self._journal_append(
                "pg",
                "put",
                {
                    "pg_id": pg_id,
                    "fields": dict(self.placement_groups[pg_id]),
                },
            )
            return {
                "ok": True,
                "nodes": [
                    {"node_id": nid, "addr": self.nodes[nid]["addr"]}
                    for nid, _ in placed
                ],
            }
        return {
            "ok": False,
            "error": f"placement retries exhausted: {last_error}",
        }

    def _plan_placement(
        self, bundles: list, strategy: str, excluded: set
    ) -> dict:
        """Pick a host node per bundle from the head's resource view.
        Returns {"ok": True, "placed": [(node_id, idx)]} or an error."""
        placed: list[tuple[str, int]] = []  # (node_id, bundle_idx)
        avail = {
            nid: dict(n["available"])
            for nid, n in self.nodes.items()
            if nid not in excluded and nid not in self.draining
        }

        def fits(nid, bundle):
            return all(avail[nid].get(k, 0) >= v for k, v in bundle.items())

        def take(nid, bundle):
            for k, v in bundle.items():
                avail[nid][k] = avail[nid].get(k, 0) - v

        node_ids = list(avail)
        if not node_ids:
            return {"ok": False, "error": "no nodes"}

        def fits_all(nid) -> bool:
            need: dict[str, float] = {}
            for b in bundles:
                for k, v in b.items():
                    need[k] = need.get(k, 0) + v
            return all(avail[nid].get(k, 0) >= v for k, v in need.items())

        if strategy == "STRICT_PACK":
            # All bundles on ONE node: try each node as the sole host.
            host = next((n for n in node_ids if fits_all(n)), None)
            if host is None:
                return {
                    "ok": False,
                    "error": "STRICT_PACK: no single node fits all bundles",
                }
            for i, bundle in enumerate(bundles):
                take(host, bundle)
                placed.append((host, i))
        else:
            used: set[str] = set()
            used_slices: set[str] = set()

            def slice_of(nid: str) -> str:
                # Unlabeled nodes are their own singleton fault domain.
                labels = self.nodes[nid].get("labels") or {}
                return labels.get("slice") or f"node:{nid}"

            for i, bundle in enumerate(bundles):
                if strategy == "PACK":
                    order = node_ids
                elif strategy == "STRICT_SPREAD":
                    # Each bundle on a DISTINCT node, or fail.
                    order = [n for n in node_ids if n not in used]
                elif strategy == "STRICT_SPREAD_SLICES":
                    # Each bundle on a DISTINCT SLICE, or fail: the
                    # cross-fault-domain gang (checkpoint replica
                    # holders, replicated services) — whole-slice loss
                    # then takes at most one bundle.
                    order = [
                        n for n in node_ids
                        if slice_of(n) not in used_slices
                    ]
                else:  # SPREAD: best-effort rotation
                    order = (
                        node_ids[i % len(node_ids) :]
                        + node_ids[: i % len(node_ids)]
                    )
                chosen = next((n for n in order if fits(n, bundle)), None)
                if chosen is None:
                    detail = ""
                    if strategy == "STRICT_SPREAD":
                        detail = (
                            " (STRICT_SPREAD needs a distinct node per "
                            "bundle)"
                        )
                    elif strategy == "STRICT_SPREAD_SLICES":
                        detail = (
                            " (STRICT_SPREAD_SLICES needs a distinct "
                            "slice per bundle)"
                        )
                    return {
                        "ok": False,
                        "error": f"bundle {i} {bundle} infeasible"
                        + detail,
                    }
                take(chosen, bundle)
                used.add(chosen)
                used_slices.add(slice_of(chosen))
                placed.append((chosen, i))
        return {"ok": True, "placed": placed}

    async def _on_remove_placement_group(self, conn, pg_id: str):
        pg = self.placement_groups.pop(pg_id, None)
        if pg is None:
            return {"ok": False}
        self._journal_append("pg", "del", {"pg_id": pg_id})
        for i, nid in enumerate(pg["nodes"]):
            node_conn = self._node_conns.get(nid)
            if node_conn is not None:
                try:
                    await node_conn.call("free_bundle", pg_id=pg_id, index=i)
                except rpc.RpcError:
                    pass
        return {"ok": True}

    async def _on_list_placement_groups(self, conn):
        return {
            "placement_groups": {
                pid: {k: v for k, v in pg.items()}
                for pid, pg in self.placement_groups.items()
            }
        }

    async def _on_get_placement_group(self, conn, pg_id: str):
        pg = self.placement_groups.get(pg_id)
        if pg is None:
            return {"ok": False}
        return {
            "ok": True,
            **pg,
            "node_addrs": [self.nodes[n]["addr"] for n in pg["nodes"]],
        }

    # ------------------------------------------------- task events/metrics
    _STATE_RANK = {
        "SUBMITTED": 0, "RUNNING": 1,
        "FINISHED": 2, "FAILED": 2, "CANCELLED": 2,
    }

    # Telemetry admission class: add_task_events only ENQUEUES (O(1)
    # amortized per event) and a background worker folds — a span flood
    # from 1000 nodes used to fold ledgers inline on the dispatch path,
    # monopolizing the loop and starving keepalives/registrations (the
    # control class). The queue is bounded: under sustained overload
    # the OLDEST events shed with an OFF→ON alert instead of unbounded
    # memory growth or latency collapse. The chunk is the fold loop's
    # scheduling quantum: control-RPC p99 under telemetry overload is
    # roughly a few chunks' worth of fold work, so it stays small.
    _FOLD_CHUNK = 64

    async def _on_add_task_events(self, conn, events: list):
        return self._enqueue_task_events(events)

    def _enqueue_task_events(self, events: list) -> dict:
        from ray_tpu._private import config

        qmax = config.get("HEAD_FOLD_QUEUE_MAX")
        q = self._fold_queue
        if (qmax if qmax > 0 else None) != q.maxlen:
            # Bound change (config override mid-run): rebuild keeping
            # the newest records, same as the shed policy.
            q = self._fold_queue = collections.deque(
                q, maxlen=qmax if qmax > 0 else None
            )
        before = len(q)
        # A maxlen deque drops from the LEFT on append — the
        # oldest-first shed is a single C-speed extend, not a Python
        # pop-per-event loop (which itself became a head hotspot at
        # 100k+ events/s of sustained overload).
        q.extend(events)
        shed = (
            max(0, before + len(events) - qmax) if qmax > 0 else 0
        )
        if shed:
            self._shed_total += shed
            if not self._overload_alert:
                self._overload_alert = True
                logger.warning(
                    "head overload: telemetry fold queue hit its "
                    "HEAD_FOLD_QUEUE_MAX=%d bound; shedding oldest "
                    "events (ray_tpu_head_shed_total)", qmax,
                )
        self._fold_wakeup.set()
        if self._fold_task is None or self._fold_task.done():
            self._fold_task = asyncio.ensure_future(self._fold_loop())
        return {"ok": True, "queued": len(q), "shed": shed}

    async def _fold_loop(self):
        """Background telemetry folder: drains the bounded queue in
        chunks, yielding to the event loop between chunks so control
        RPCs interleave even under a sustained span flood."""
        from ray_tpu._private.test_utils import head_stall_for

        while True:
            if not self._fold_queue:
                self._fold_wakeup.clear()
                if self._overload_alert:
                    # OFF transition: the backlog fully drained.
                    self._overload_alert = False
                    logger.info(
                        "head overload cleared: telemetry fold queue "
                        "drained (lifetime shed total %d)",
                        self._shed_total,
                    )
                await self._fold_wakeup.wait()
            stall = head_stall_for("fold")
            if stall > 0:
                await asyncio.sleep(stall)
            n = 0
            q = self._fold_queue
            while q and n < self._FOLD_CHUNK:
                self._fold_one(q.popleft())
                n += 1
            await asyncio.sleep(0)

    def _drain_folds(self) -> None:
        """Fold everything queued NOW. Read-your-writes for the state
        surfaces: a worker that flushed telemetry and then queries
        stats/events must see it folded, queue or no queue."""
        q = self._fold_queue
        while q:
            self._fold_one(q.popleft())

    def _fold_one(self, ev: dict) -> None:
        self._folded_total += 1
        self.task_events.append(ev)
        tid = ev.get("task_id")
        if ev.get("state") == "SPAN":
            # Spans live in the raw stream only, not the merged task
            # table (they would evict real task states). Rank-0 train
            # step spans additionally drive per-job goodput.
            if ev.get("name") == "train:step" and ev.get("train_job"):
                self._train_step_event(ev)
            # Ingress spans additionally drive the per-deployment
            # serve SLO ledger.
            elif (
                ev.get("name") == "serve:ingress"
                and ev.get("deployment")
            ):
                self._serve_request_event(ev)
            # Per-node memory samples additionally drive the head
            # memory ledger.
            elif ev.get("name") == "mem:sample" and ev.get("mem_node"):
                self._mem_event(ev)
            # Capture reports additionally drive the MFU-decomposition
            # ledger and the profile regression sentinel.
            elif (
                ev.get("name") == "profile:step"
                and ev.get("train_job")
            ):
                self._profile_step_event(ev)
            return
        if tid:
            prev = self.task_latest.pop(tid, None)
            merged = dict(prev or {})
            # Events from different processes arrive out of order
            # (driver flushes FINISHED; the worker's RUNNING may land
            # later) — never let a terminal state regress.
            old_state = merged.get("state")
            merged.update(ev)
            if old_state is not None and self._STATE_RANK.get(
                ev.get("state"), 0
            ) < self._STATE_RANK.get(old_state, 0):
                merged["state"] = old_state
            self.task_latest[tid] = merged
            while len(self.task_latest) > 20000:
                self.task_latest.popitem(last=False)

    async def _on_list_task_events(
        self,
        conn,
        limit: int = 1000,
        raw: bool = False,
        state: str | None = None,
    ):
        """`state` filters BEFORE `limit` applies: a span query must not
        come back empty just because busy task traffic fills the
        newest-N window."""
        self._drain_folds()  # read-your-writes past the fold queue
        if raw:
            events = list(self.task_events)
            if state is not None:
                events = [e for e in events if e.get("state") == state]
            return {"events": events[-limit:]}
        items = list(self.task_latest.values())
        if state is not None:
            items = [e for e in items if e.get("state") == state]
        return {"events": items[-limit:]}

    # ------------------------------------------------- train goodput
    def _train_step_event(self, ev: dict) -> None:
        """Fold one rank-0 train-step span into the job's goodput
        ledger. Attempt boundaries (TrainContext.attempt) mark elastic
        restarts: the wall-clock hole between attempts is restart-lost
        time, including any partial step the dying attempt never
        finished."""
        if ev.get("train_rank") != 0:
            return
        job = str(ev["train_job"])
        rec = self.train_runs.get(job)
        if rec is None:
            if len(self.train_runs) >= 200:
                oldest = min(
                    self.train_runs, key=lambda j: self.train_runs[j]["first_ts"]
                )
                del self.train_runs[oldest]
            rec = self.train_runs[job] = {
                "attempt": -1,
                "attempts_seen": 0,
                "steps": 0,
                "productive_s": 0.0,
                "stall_s": 0.0,
                "degraded_s": 0.0,
                "restart_lost_s": 0.0,
                # comm-exposure attribution (rank 0's step spans):
                # collective seconds NOT hidden behind compute vs the
                # overlapped remainder, and the step-second denominator.
                "comm_exposed_s": 0.0,
                "comm_overlapped_s": 0.0,
                # Host-sync exposure (PR 13's sanitizer tracer): wall
                # seconds of block_until_ready/device_get inside the
                # compute phase — the host-side twin of comm_exposed_s.
                "host_sync_exposed_s": 0.0,
                "step_s": 0.0,
                "first_ts": float(ev.get("ts") or 0.0),
                "last_end_ts": None,
                "mfu": None,
                # Latest reported training loss (train:step span attr):
                # what the sweep engine's ledger-driven schedulers rank
                # trials by — no reporting path beyond the span fold.
                "loss": None,
                "phase_s": {},
                # sliding alert window: (step_end_ts, total_s, lost_s)
                "window": [],
                "alert": False,
            }
        try:
            attempt = int(ev.get("train_attempt") or 0)
            start = float(ev["ts"])
            dur = max(0.0, float(ev.get("dur") or 0.0))
        except (TypeError, ValueError):
            return
        if attempt < rec["attempt"]:
            return  # straggling flush from a superseded attempt
        gap = 0.0
        if attempt > rec["attempt"]:
            if rec["attempt"] >= 0 and rec["last_end_ts"] is not None:
                rec["restart_lost_s"] += max(
                    0.0, start - rec["last_end_ts"]
                )
            rec["attempt"] = attempt
            rec["attempts_seen"] += 1
        elif rec["last_end_ts"] is not None:
            # Same attempt: the hole between consecutive steps is stall.
            gap = max(0.0, start - rec["last_end_ts"])
            rec["stall_s"] += gap
        phases = ev.get("phases") or {}
        in_step_lost = 0.0
        for ph, s in phases.items():
            try:
                s = float(s)
            except (TypeError, ValueError):
                continue
            rec["phase_s"][ph] = rec["phase_s"].get(ph, 0.0) + s
            if ph in ("data_wait", "checkpoint"):
                in_step_lost += s
        in_step_lost = min(in_step_lost, dur)
        # Degraded: the fraction of this step a partial collective ran
        # without every rank's contribution — progress was made, but on
        # a thinner gradient; a category of its own so "slow because
        # skipping" never masquerades as productive OR as stall.
        try:
            dfrac = min(1.0, max(0.0, float(ev.get("degraded_frac") or 0.0)))
        except (TypeError, ValueError):
            dfrac = 0.0
        degraded = min(dfrac * dur, dur - in_step_lost)
        rec["steps"] += 1
        rec["productive_s"] += dur - in_step_lost - degraded
        rec["degraded_s"] += degraded
        rec["stall_s"] += in_step_lost
        rec["step_s"] += dur
        for key in (
            "comm_exposed_s", "comm_overlapped_s", "host_sync_exposed_s",
        ):
            try:
                rec[key] += max(0.0, float(ev.get(key) or 0.0))
            except (TypeError, ValueError):
                pass
        if isinstance(ev.get("mfu"), (int, float)):
            rec["mfu"] = float(ev["mfu"])
        if isinstance(ev.get("loss"), (int, float)):
            rec["loss"] = float(ev["loss"])
        rec["last_end_ts"] = max(rec["last_end_ts"] or 0.0, start + dur)
        self._goodput_alert_check(
            job, rec, start + dur, dur + gap, gap + in_step_lost + degraded
        )

    def _goodput_alert_check(
        self, job: str, rec: dict, end_ts: float, total_s: float,
        lost_s: float,
    ) -> None:
        """Per-phase goodput alerting: warn (log + gauge) when the lost
        fraction — inter-step stalls, data-wait/checkpoint phases, and
        the degraded partial-collective fraction — over the sliding
        window exceeds the configured ratio. Log fires on the OFF→ON
        transition only; the gauge tracks the current state."""
        from ray_tpu._private import config

        window_s = config.get("TRAIN_GOODPUT_ALERT_WINDOW_S")
        ratio = config.get("TRAIN_GOODPUT_ALERT_RATIO")
        rec["window"].append((end_ts, total_s, lost_s))
        cutoff = end_ts - window_s
        rec["window"] = [w for w in rec["window"] if w[0] >= cutoff]
        total = sum(w[1] for w in rec["window"])
        lost = sum(w[2] for w in rec["window"])
        alert = total > 0 and lost / total > ratio
        if alert and not rec["alert"]:
            logger.warning(
                "train job %r: %.0f%% of the last %.0fs was lost to "
                "stalls/degraded collectives (alert ratio %.0f%%)",
                job, 100.0 * lost / total, window_s, 100.0 * ratio,
            )
        rec["alert"] = alert

    @staticmethod
    def _train_job_public(rec: dict) -> dict:
        denom = (
            rec["productive_s"] + rec["stall_s"] + rec["degraded_s"]
            + rec["restart_lost_s"]
        )
        step_s = rec.get("step_s", 0.0)
        exposed = rec.get("comm_exposed_s", 0.0)
        return {
            "goodput": rec["productive_s"] / denom if denom > 0 else 1.0,
            "productive_s": rec["productive_s"],
            "stall_s": rec["stall_s"],
            "degraded_s": rec["degraded_s"],
            "restart_lost_s": rec["restart_lost_s"],
            "comm_exposed_s": exposed,
            "comm_overlapped_s": rec.get("comm_overlapped_s", 0.0),
            "comm_exposed_ratio": (
                exposed / step_s if step_s > 0 else 0.0
            ),
            "host_sync_exposed_s": rec.get("host_sync_exposed_s", 0.0),
            "host_sync_exposed_ratio": (
                rec.get("host_sync_exposed_s", 0.0) / step_s
                if step_s > 0 else 0.0
            ),
            "steps": rec["steps"],
            "attempts": rec["attempts_seen"],
            "current_attempt": rec["attempt"],
            "mfu": rec["mfu"],
            "loss": rec.get("loss"),
            "phase_s": dict(rec["phase_s"]),
            "first_ts": rec["first_ts"],
            "last_ts": rec["last_end_ts"],
            "alert": rec["alert"],
        }

    async def _on_train_stats(self, conn):
        """Per-job goodput/MFU rollup (dashboard /api/train, agent
        passthrough, `ray_tpu goodput`). The ONE fold path joining the
        goodput ledger with the profiler's in-program decomposition:
        a job with a capture report carries it under "profile"."""
        self._drain_folds()  # read-your-writes past the fold queue
        jobs = {}
        for job, rec in self.train_runs.items():
            pub = self._train_job_public(rec)
            prof = self.profile_runs.get(job)
            if prof is not None:
                pub["profile"] = self._profile_public(prof)
            jobs[job] = pub
        return {"jobs": jobs}

    # ------------------------------------- compiled-program profiler
    def _profile_step_event(self, ev: dict) -> None:
        """Fold one rank-0 ``profile:step`` span (train/profile.py's
        capture report) into the decomposition ledger and run the
        regression sentinel against the journaled fingerprint for the
        step signature. First sight of a signature RECORDS the
        fingerprint; later captures compare against it."""
        if ev.get("train_rank") != 0:
            return
        job = str(ev["train_job"])
        shares = ev.get("profile_shares")
        if not isinstance(shares, dict):
            return
        clean: dict[str, float] = {}
        for cat, v in shares.items():
            if isinstance(v, (int, float)):
                clean[str(cat)] = float(v)
        if not clean:
            return
        sig = str(ev.get("profile_sig") or job)
        try:
            step_s = float(ev.get("profile_step_s") or 0.0)
            steps = int(ev.get("profile_steps") or 0)
            ts = float(ev.get("ts") or 0.0)
        except (TypeError, ValueError):
            return
        rec = {
            "sig": sig,
            "shares": clean,
            "step_s": step_s,
            "steps": steps,
            "dominant_gap": str(ev.get("profile_dominant") or ""),
            "path": str(ev.get("path") or ""),
            "ts": ts,
            "alert": False,
            "drift": {},
        }
        baseline = self.profile_fp.get(sig)
        if baseline is None:
            fp = {
                "job": job,
                "shares": dict(clean),
                "step_s": step_s,
                "ts": ts,
            }
            self.profile_fp[sig] = fp
            self._journal_append(
                "profile", "put", {"sig": sig, "fields": fp}
            )
        else:
            self._profile_regression_check(job, rec, baseline)
        if job not in self.profile_runs and len(self.profile_runs) >= 200:
            oldest = min(
                self.profile_runs,
                key=lambda j: self.profile_runs[j]["ts"],
            )
            del self.profile_runs[oldest]
        self.profile_runs[job] = rec

    def _profile_regression_check(
        self, job: str, rec: dict, baseline: dict
    ) -> None:
        """Flag category shares that drifted past
        PROFILE_REGRESSION_PCT relative to the fingerprint. Shares
        under 2% on both sides are noise, not regressions; the
        denominator is floored at 2% so a tiny baseline can't turn
        rounding into an alert. Warn-log fires on the OFF→ON
        transition only; the gauge tracks current state."""
        from ray_tpu._private import config

        pct = config.get("PROFILE_REGRESSION_PCT") / 100.0
        drift: dict[str, float] = {}
        cats = set(baseline.get("shares", {})) | set(rec["shares"])
        for cat in cats:
            base = float(baseline.get("shares", {}).get(cat, 0.0))
            cur = rec["shares"].get(cat, 0.0)
            if base < 0.02 and cur < 0.02:
                continue
            d = (cur - base) / max(base, 0.02)
            if abs(d) > pct:
                drift[cat] = round(d, 4)
        rec["drift"] = drift
        rec["alert"] = bool(drift)
        prev = self.profile_runs.get(job)
        if rec["alert"] and not (prev and prev.get("alert")):
            logger.warning(
                "train job %r: profile regression vs fingerprint %s — "
                "category share drift past %.0f%%: %s",
                job, rec["sig"], 100.0 * pct, drift,
            )

    @staticmethod
    def _profile_public(rec: dict) -> dict:
        return {
            "sig": rec["sig"],
            "shares": dict(rec["shares"]),
            "step_s": rec["step_s"],
            "steps": rec["steps"],
            "dominant_gap": rec["dominant_gap"],
            "drift": dict(rec["drift"]),
            "alert": rec["alert"],
            "path": rec["path"],
            "ts": rec["ts"],
        }

    async def _on_profile_stats(self, conn):
        """Per-job MFU decomposition + fingerprints (dashboard
        /api/profile, `ray_tpu profile`)."""
        self._drain_folds()  # read-your-writes past the fold queue
        return {
            "jobs": {
                job: self._profile_public(rec)
                for job, rec in self.profile_runs.items()
            },
            "fingerprints": {
                sig: dict(rec)
                for sig, rec in self.profile_fp.items()
            },
        }

    async def _on_profile_capture(self, conn, steps: int | None = None):
        """Fan a capture request out to every rank: riders of the
        "collective" channel (the same fan-out that delivers member
        death and drain notices) arm their local per-step profiler
        hook; reports come back as ``profile:step`` spans on the
        ordinary telemetry pipeline."""
        msg = {"event": "profile_capture"}
        if steps is not None:
            msg["steps"] = int(steps)
        self.publish("collective", msg)
        return {"ok": True, "steps": steps}

    def _profile_metrics_snapshot(self) -> dict | None:
        """Head-owned profiler gauges in worker-snapshot format: the
        per-category MFU decomposition and the regression-sentinel
        alert, attributed to the head pseudo-worker like the goodput
        gauges."""
        if not self.profile_runs:
            return None
        from ray_tpu.util.metrics import escape_label_value as _esc

        decomp: dict[str, float] = {}
        alert: dict[str, float] = {}
        for job, rec in self.profile_runs.items():
            jtag = f'job="{_esc(job)}"'
            for cat, share in rec["shares"].items():
                decomp[f'{jtag},category="{_esc(cat)}"'] = round(
                    share, 6
                )
            alert[jtag] = 1.0 if rec["alert"] else 0.0
        return {
            "ray_tpu_train_mfu_decomposition": {
                "kind": "gauge",
                "description": "share of the measured step wall per "
                               "profiler category (compute_floor/"
                               "comm_in_program/hbm_bound/host_gap/"
                               "unattributed), from the latest "
                               "compiled-program capture",
                "series": decomp,
                "boundaries": None,
            },
            "ray_tpu_profile_regression_alert": {
                "kind": "gauge",
                "description": "1 when a category's share drifted "
                               "past PROFILE_REGRESSION_PCT vs the "
                               "journaled fingerprint for the job's "
                               "step signature",
                "series": alert,
                "boundaries": None,
            },
        }

    # --------------------------------------------------- serve SLO ledger
    def _serve_request_event(self, ev: dict) -> None:
        """Fold one proxy ``serve:ingress`` span into the deployment's
        SLO ledger (the serving twin of _train_step_event). A request
        ATTAINS its SLO when it succeeded AND its TTFT is within
        SERVE_SLO_TTFT_S AND its end-to-end latency is within
        SERVE_SLO_LATENCY_S; attainment over the sliding window below
        SERVE_SLO_TARGET flips the burn-rate alert."""
        key = f'{ev.get("app") or "default"}/{ev["deployment"]}'
        rec = self.serve_runs.get(key)
        if rec is None:
            if len(self.serve_runs) >= 200:
                oldest = min(
                    self.serve_runs,
                    key=lambda k: self.serve_runs[k]["first_ts"],
                )
                del self.serve_runs[oldest]
            rec = self.serve_runs[key] = {
                "requests": 0,
                "errors": 0,
                "streamed": 0,
                "items": 0,
                "first_ts": float(ev.get("ts") or 0.0),
                "last_ts": None,
                # sliding window: (end_ts, latency_s, ttft_s, attained)
                "window": [],
                "alert": False,
            }
        try:
            start = float(ev["ts"])
            dur = max(0.0, float(ev.get("dur") or 0.0))
        except (TypeError, ValueError):
            return
        try:
            ttft = float(ev.get("ttft_s")) if ev.get("ttft_s") is not None \
                else dur
        except (TypeError, ValueError):
            ttft = dur
        try:
            status = int(ev.get("status") or 0)
        except (TypeError, ValueError):
            status = 0
        from ray_tpu._private import config

        ok = status < 400
        attained = (
            ok
            and ttft <= config.get("SERVE_SLO_TTFT_S")
            and dur <= config.get("SERVE_SLO_LATENCY_S")
        )
        rec["requests"] += 1
        rec["errors"] += 0 if ok else 1
        rec["streamed"] += 1 if ev.get("streamed") else 0
        try:
            rec["items"] += int(ev.get("items") or 0)
        except (TypeError, ValueError):
            pass
        end_ts = start + dur
        rec["last_ts"] = max(rec["last_ts"] or 0.0, end_ts)
        window_s = config.get("SERVE_SLO_WINDOW_S")
        rec["window"].append((end_ts, dur, ttft, attained))
        cutoff = end_ts - window_s
        rec["window"] = [w for w in rec["window"] if w[0] >= cutoff]
        attain_frac = (
            sum(1 for w in rec["window"] if w[3]) / len(rec["window"])
            if rec["window"] else 1.0
        )
        alert = (
            bool(rec["window"])
            and attain_frac < config.get("SERVE_SLO_TARGET")
        )
        if alert and not rec["alert"]:
            logger.warning(
                "serve deployment %r: SLO attainment %.0f%% over the "
                "last %.0fs fell below the %.0f%% target "
                "(ttft<=%.2fs, latency<=%.2fs)",
                key, 100.0 * attain_frac, window_s,
                100.0 * config.get("SERVE_SLO_TARGET"),
                config.get("SERVE_SLO_TTFT_S"),
                config.get("SERVE_SLO_LATENCY_S"),
            )
        rec["alert"] = alert

    @staticmethod
    def _percentile(values: list[float], q: float) -> float | None:
        if not values:
            return None
        ordered = sorted(values)
        idx = min(len(ordered) - 1, int(q * (len(ordered) - 1) + 0.5))
        return ordered[idx]

    def _serve_deployment_public(self, key: str, rec: dict) -> dict:
        from ray_tpu._private import config

        ttfts = [w[2] for w in rec["window"]]
        lats = [w[1] for w in rec["window"]]
        attained = sum(1 for w in rec["window"] if w[3])
        n = len(rec["window"])
        window_s = config.get("SERVE_SLO_WINDOW_S")
        return {
            "requests": rec["requests"],
            "errors": rec["errors"],
            "streamed": rec["streamed"],
            "items": rec["items"],
            "window_requests": n,
            # The autoscaler's rate signal: requests finishing per
            # second over the SLO window.
            "request_rate_per_s": (
                n / window_s if window_s > 0 else 0.0
            ),
            "ttft_p50_s": self._percentile(ttfts, 0.50),
            "ttft_p99_s": self._percentile(ttfts, 0.99),
            "latency_p50_s": self._percentile(lats, 0.50),
            "latency_p99_s": self._percentile(lats, 0.99),
            "attainment": attained / n if n else 1.0,
            "alert": rec["alert"],
            "first_ts": rec["first_ts"],
            "last_ts": rec["last_ts"],
            # The control loop's last word on this deployment (None
            # until a controller reports).
            "autoscale": self.serve_autoscale.get(key),
        }

    async def _on_serve_stats(self, conn):
        """Per-deployment serve SLO rollup (dashboard /api/serve, agent
        passthrough, `ray_tpu slo`) — the ledger-read API the serve
        control loop polls for attainment/alert/request-rate, plus the
        autoscale decisions it reported back."""
        self._drain_folds()  # read-your-writes past the fold queue
        out = {
            key: self._serve_deployment_public(key, rec)
            for key, rec in self.serve_runs.items()
        }
        # Deployments that reported autoscale state but have no ledger
        # rows yet (no proxy traffic since boot) still surface their
        # targets — schema-complete, so /api/serve consumers see one
        # row shape.
        for key, asc in self.serve_autoscale.items():
            if key not in out:
                out[key] = {
                    "requests": 0, "errors": 0, "streamed": 0,
                    "items": 0, "window_requests": 0,
                    "request_rate_per_s": 0.0,
                    "ttft_p50_s": None, "ttft_p99_s": None,
                    "latency_p50_s": None, "latency_p99_s": None,
                    "attainment": 1.0, "alert": False,
                    "first_ts": None, "last_ts": None,
                    "autoscale": asc,
                }
        return {"deployments": out}

    async def _on_serve_autoscale_report(
        self,
        conn,
        app: str,
        deployment: str,
        target: int,
        replicas: int = 0,
        draining: int = 0,
        desired: "int | None" = None,
        reason: "str | None" = None,
    ):
        """Controller → head: one deployment's current autoscale state
        (target, live/draining replica counts, last decision). Folded
        into serve_stats and the ray_tpu_serve_target_replicas gauge."""
        key = f"{app or 'default'}/{deployment}"
        if key not in self.serve_autoscale and \
                len(self.serve_autoscale) >= 200:
            oldest = min(
                self.serve_autoscale,
                key=lambda k: self.serve_autoscale[k]["ts"],
            )
            del self.serve_autoscale[oldest]
        self.serve_autoscale[key] = {
            "target": int(target),
            "replicas": int(replicas),
            "draining": int(draining),
            "desired": desired if desired is None else int(desired),
            "reason": reason,
            "ts": time.time(),
        }
        return {"ok": True}

    # --------------------------------------------------- memory ledger
    def _mem_event(self, ev: dict) -> None:
        """Fold one ``mem:sample`` span into the per-node (and per-job)
        memory ledger — the memory twin of _train_step_event /
        _serve_request_event. Headroom below
        MEM_HEADROOM_ALERT_FRACTION of capacity flips the node's alert
        with an OFF→ON warn log."""
        node = str(ev["mem_node"])
        rec = self.mem_nodes.get(node)
        if rec is None:
            if len(self.mem_nodes) >= 500:
                oldest = min(
                    self.mem_nodes,
                    key=lambda n: self.mem_nodes[n]["first_ts"],
                )
                del self.mem_nodes[oldest]
            rec = self.mem_nodes[node] = {
                "used_bytes": 0,
                "peak_bytes": 0,
                "capacity_bytes": None,
                "headroom_bytes": None,
                "host_rss_bytes": None,
                "by_kind": {},
                "samples": 0,
                "alert": False,
                "first_ts": float(ev.get("ts") or 0.0),
                "last_ts": None,
            }
        try:
            used = int(ev.get("mem_used_bytes") or 0)
            peak = int(ev.get("mem_peak_bytes") or used)
        except (TypeError, ValueError):
            return
        cap = ev.get("mem_capacity_bytes")
        try:
            cap = int(cap) if cap is not None else None
        except (TypeError, ValueError):
            cap = None
        rec["used_bytes"] = used
        rec["peak_bytes"] = max(rec["peak_bytes"], peak)
        rec["capacity_bytes"] = cap
        rec["headroom_bytes"] = cap - used if cap is not None else None
        rss = ev.get("mem_host_rss_bytes")
        rec["host_rss_bytes"] = int(rss) if isinstance(rss, int) else None
        by_kind = ev.get("mem_by_kind")
        # Keep the last non-empty attribution: the emitter drops zero
        # kinds, so an idle sample's {} must not wipe what we know
        # about who owned the bytes.
        if isinstance(by_kind, dict) and by_kind:
            rec["by_kind"] = {
                str(k): int(v)
                for k, v in by_kind.items()
                if isinstance(v, (int, float))
            }
        rec["samples"] += 1
        rec["last_ts"] = float(ev.get("ts") or 0.0)
        from ray_tpu._private import config

        frac = config.get("MEM_HEADROOM_ALERT_FRACTION")
        alert = bool(
            cap and rec["headroom_bytes"] is not None
            and rec["headroom_bytes"] < cap * frac
        )
        if alert and not rec["alert"]:
            top = sorted(
                rec["by_kind"].items(), key=lambda kv: -kv[1]
            )[:3]
            logger.warning(
                "node %s device memory headroom low: %.2f GiB free of "
                "%.2f GiB (alert below %.0f%%) — top kinds: %s",
                node, (rec["headroom_bytes"] or 0) / (1 << 30),
                cap / (1 << 30), 100.0 * frac,
                ", ".join(
                    f"{k}={v / (1 << 30):.2f}GiB" for k, v in top
                ) or "none registered",
            )
        rec["alert"] = alert
        job = ev.get("mem_job")
        if job:
            jrec = self.mem_jobs.get(str(job))
            if jrec is None:
                if len(self.mem_jobs) >= 200:
                    oldest = min(
                        self.mem_jobs,
                        key=lambda j: self.mem_jobs[j]["first_ts"],
                    )
                    del self.mem_jobs[oldest]
                jrec = self.mem_jobs[str(job)] = {
                    "peak_bytes": 0,
                    "used_bytes": 0,
                    "nodes": [],
                    "first_ts": float(ev.get("ts") or 0.0),
                    "last_ts": None,
                }
            jrec["peak_bytes"] = max(jrec["peak_bytes"], peak)
            jrec["used_bytes"] = used
            if node not in jrec["nodes"]:
                jrec["nodes"].append(node)
            jrec["last_ts"] = float(ev.get("ts") or 0.0)

    async def _on_mem_stats(self, conn):
        """Per-node and per-job memory rollup (dashboard /api/memory,
        agent passthrough, `ray_tpu mem`)."""
        self._drain_folds()  # read-your-writes past the fold queue
        return {
            "nodes": {n: dict(rec) for n, rec in self.mem_nodes.items()},
            "jobs": {j: dict(rec) for j, rec in self.mem_jobs.items()},
        }

    def _mem_metrics_snapshot(self) -> dict | None:
        """Head-owned memory gauges in worker-snapshot format (the
        memory twin of _serve_metrics_snapshot): per-node used/peak/
        headroom-alert, surviving the workers they were sampled at."""
        if not self.mem_nodes:
            return None
        from ray_tpu.util.metrics import escape_label_value as _esc

        used: dict[str, float] = {}
        peak: dict[str, float] = {}
        alert: dict[str, float] = {}
        for node, rec in self.mem_nodes.items():
            tag = f'node="{_esc(node)}"'
            used[tag] = float(rec["used_bytes"])
            peak[tag] = float(rec["peak_bytes"])
            alert[tag] = 1.0 if rec["alert"] else 0.0
        return {
            "ray_tpu_mem_node_used_bytes": {
                "kind": "gauge",
                "description": "device bytes in use at each node's "
                               "last memory sample",
                "series": used,
                "boundaries": None,
            },
            "ray_tpu_mem_node_peak_bytes": {
                "kind": "gauge",
                "description": "peak device bytes in use each node has "
                               "reported",
                "series": peak,
                "boundaries": None,
            },
            "ray_tpu_mem_headroom_alert": {
                "kind": "gauge",
                "description": "1 when a node's device headroom is "
                               "below MEM_HEADROOM_ALERT_FRACTION of "
                               "capacity",
                "series": alert,
                "boundaries": None,
            },
        }

    def _serve_metrics_snapshot(self) -> dict | None:
        """Head-owned serve SLO gauges in worker-snapshot format (the
        serving twin of _train_metrics_snapshot): attainment + alert per
        deployment, surviving the proxies they were measured at."""
        if not self.serve_runs and not self.serve_autoscale:
            return None
        from ray_tpu.util.metrics import escape_label_value as _esc

        attain: dict[str, float] = {}
        alert: dict[str, float] = {}
        for key, rec in self.serve_runs.items():
            pub = self._serve_deployment_public(key, rec)
            tag = f'deployment="{_esc(key)}"'
            attain[tag] = round(pub["attainment"], 6)
            alert[tag] = 1.0 if rec["alert"] else 0.0
        target: dict[str, float] = {}
        for key, asc in self.serve_autoscale.items():
            target[f'deployment="{_esc(key)}"'] = float(asc["target"])
        out_extra = (
            {
                "ray_tpu_serve_target_replicas": {
                    "kind": "gauge",
                    "description": "controller-reported target replica "
                                   "count per deployment (the "
                                   "autoscaler's output)",
                    "series": target,
                    "boundaries": None,
                },
            }
            if target
            else {}
        )
        return {
            **out_extra,
            "ray_tpu_serve_slo_attainment": {
                "kind": "gauge",
                "description": "fraction of requests meeting their "
                               "TTFT/latency SLO over the sliding "
                               "window, per deployment",
                "series": attain,
                "boundaries": None,
            },
            "ray_tpu_serve_slo_alert": {
                "kind": "gauge",
                "description": "1 when a deployment's SLO attainment "
                               "over the window is below "
                               "SERVE_SLO_TARGET",
                "series": alert,
                "boundaries": None,
            },
        }

    def _train_metrics_snapshot(self) -> dict | None:
        """Head-owned train gauges in worker-snapshot format, merged
        into cluster_metrics under the pseudo-worker "head" — goodput
        survives the workers (and attempts) it is computed from."""
        if not self.train_runs:
            return None
        from ray_tpu.util.metrics import escape_label_value as _esc

        gp: dict[str, float] = {}
        lost: dict[str, float] = {}
        degraded: dict[str, float] = {}
        alert: dict[str, float] = {}
        mfu: dict[str, float] = {}
        for job, rec in self.train_runs.items():
            pub = self._train_job_public(rec)
            tag = f'job="{_esc(job)}"'
            gp[tag] = round(pub["goodput"], 6)
            lost[tag] = round(rec["restart_lost_s"], 6)
            degraded[tag] = round(rec["degraded_s"], 6)
            alert[tag] = 1.0 if rec["alert"] else 0.0
            if rec["mfu"] is not None:
                mfu[tag] = rec["mfu"]
        out = {
            "ray_tpu_train_goodput_ratio": {
                "kind": "gauge",
                "description": "productive step time / (productive + "
                               "stalls + degraded + restart loss) per "
                               "train job",
                "series": gp,
                "boundaries": None,
            },
            "ray_tpu_train_restart_lost_seconds": {
                "kind": "gauge",
                "description": "wall time lost to elastic attempt "
                               "restarts per train job",
                "series": lost,
                "boundaries": None,
            },
            "ray_tpu_train_degraded_seconds": {
                "kind": "gauge",
                "description": "step time degraded by partial "
                               "collectives skipping straggler "
                               "contributions, per train job",
                "series": degraded,
                "boundaries": None,
            },
            "ray_tpu_train_goodput_alert": {
                "kind": "gauge",
                "description": "1 when the job's stall+degraded "
                               "fraction over the alert window exceeds "
                               "TRAIN_GOODPUT_ALERT_RATIO",
                "series": alert,
                "boundaries": None,
            },
        }
        if mfu:
            out["ray_tpu_train_mfu"] = {
                "kind": "gauge",
                "description": "model FLOPs utilization of this "
                               "worker's most recent step",
                "series": mfu,
                "boundaries": None,
            }
        return out

    # ------------------------------------------------------ sweep table
    async def _on_sweep_put(self, conn, sweep_id: str, fields: dict):
        """Upsert sweep-level orchestrator state (scheduler, sample
        count, fork/preemption counters, terminal status). Journaled:
        the sweep table is what a restarted head — or a restarted
        orchestrator reading sweep_stats — resumes from."""
        rec = self.sweeps.setdefault(sweep_id, {"trials": {}})
        clean = {k: v for k, v in dict(fields).items() if k != "trials"}
        rec.update(clean)
        self._journal_append(
            "sweep", "put", {"sweep_id": sweep_id, "fields": clean}
        )
        return {"ok": True}

    async def _on_sweep_trial(
        self, conn, sweep_id: str, trial_id: str, fields: dict
    ):
        """Upsert one trial's durable record (state transitions, rung
        promotions, fork lineage, migration target)."""
        rec = self.sweeps.setdefault(sweep_id, {"trials": {}})
        rec["trials"].setdefault(trial_id, {}).update(dict(fields))
        self._journal_append(
            "sweep",
            "trial",
            {
                "sweep_id": sweep_id,
                "trial_id": trial_id,
                "fields": dict(fields),
            },
        )
        return {"ok": True}

    async def _on_sweep_stats(self, conn, sweep_id: str | None = None):
        """Sweep table joined against the goodput ledger: each trial
        that names a train job gets that job's public ledger row
        (goodput, steps, restart_lost_s …) inlined, so the scheduler,
        dashboard /api/tune, and `ray_tpu tune` read ONE surface."""
        self._drain_folds()  # read-your-writes past the fold queue
        out = {}
        items = (
            [(sweep_id, self.sweeps[sweep_id])]
            if sweep_id is not None and sweep_id in self.sweeps
            else list(self.sweeps.items())
        )
        for sid, rec in items:
            trials = {}
            for tid, t in rec.get("trials", {}).items():
                pub = dict(t)
                job = t.get("job")
                run = self.train_runs.get(job) if job else None
                if run is not None:
                    pub["ledger"] = self._train_job_public(run)
                trials[tid] = pub
            out[sid] = {
                **{k: v for k, v in rec.items() if k != "trials"},
                "trials": trials,
            }
        return {"sweeps": out}

    def _tune_metrics_snapshot(self) -> dict | None:
        """Head-owned sweep gauges in worker-snapshot format (the tune
        twin of _train_metrics_snapshot): per-sweep trial-state counts
        plus fork/preemption counters, surviving the orchestrator that
        reported them."""
        if not self.sweeps:
            return None
        from ray_tpu.util.metrics import escape_label_value as _esc

        running: dict[str, float] = {}
        done: dict[str, float] = {}
        errored: dict[str, float] = {}
        forks: dict[str, float] = {}
        preempt: dict[str, float] = {}
        for sid, rec in self.sweeps.items():
            tag = f'sweep="{_esc(sid)}"'
            states = [
                t.get("state") for t in rec.get("trials", {}).values()
            ]
            running[tag] = float(
                sum(1 for s in states if s in ("RUNNING", "PENDING"))
            )
            done[tag] = float(
                sum(1 for s in states if s == "TERMINATED")
            )
            errored[tag] = float(
                sum(1 for s in states if s == "ERROR")
            )
            forks[tag] = float(rec.get("forks", 0))
            preempt[tag] = float(rec.get("preemptions", 0))
        return {
            "ray_tpu_tune_trials_running": {
                "kind": "gauge",
                "description": "trials pending admission or running, "
                               "per sweep",
                "series": running,
                "boundaries": None,
            },
            "ray_tpu_tune_trials_terminated": {
                "kind": "gauge",
                "description": "trials finished or stopped at a rung "
                               "boundary, per sweep",
                "series": done,
                "boundaries": None,
            },
            "ray_tpu_tune_trials_errored": {
                "kind": "gauge",
                "description": "trials failed on a non-retryable "
                               "error, per sweep",
                "series": errored,
                "boundaries": None,
            },
            "ray_tpu_tune_forks_total": {
                "kind": "gauge",
                "description": "PBT checkpoint forks performed (each "
                               "a zero-byte manifest copy), per sweep",
                "series": forks,
                "boundaries": None,
            },
            "ray_tpu_tune_preemptions_total": {
                "kind": "gauge",
                "description": "trial preemptions/migrations absorbed "
                               "by re-admission, per sweep",
                "series": preempt,
                "boundaries": None,
            },
        }

    METRICS_TTL_S = 60.0

    async def _on_report_metrics(self, conn, worker: str, metrics: dict):
        self.metrics[worker] = {"ts": time.monotonic(), "snap": metrics}
        return {"ok": True}

    async def _on_cluster_metrics(self, conn):
        # Entries from workers that stopped reporting (exited job
        # drivers, dead workers) age out — otherwise the map grows with
        # every short-lived job and dead gauges report forever.
        now = time.monotonic()
        self._drain_folds()  # ledger gauges must reflect queued spans
        for w, rec in list(self.metrics.items()):
            if now - rec["ts"] > self.METRICS_TTL_S:
                del self.metrics[w]
        workers = {w: rec["snap"] for w, rec in self.metrics.items()}
        head_snap = dict(self._train_metrics_snapshot() or {})
        head_snap.update(self._serve_metrics_snapshot() or {})
        head_snap.update(self._mem_metrics_snapshot() or {})
        head_snap.update(self._profile_metrics_snapshot() or {})
        head_snap.update(self._tune_metrics_snapshot() or {})
        head_snap.update(self._head_metrics_snapshot())
        if head_snap:
            workers["head"] = head_snap
        return {"workers": workers}

    def _head_metrics_snapshot(self) -> dict:
        """Head-load gauges in worker-snapshot format: the overload-
        protection surface (shed counter + OFF→ON alert + queue depth)
        and pubsub coalescing counters, attributed to the head pseudo-
        worker like the ledger gauges above."""
        tag = 'node="head"'
        return {
            "ray_tpu_head_shed_total": {
                "kind": "gauge",
                "description": "telemetry events shed by the bounded "
                               "head fold queue (lifetime; >0 means "
                               "the head ran past HEAD_FOLD_QUEUE_MAX)",
                "series": {tag: float(self._shed_total)},
                "boundaries": None,
            },
            "ray_tpu_head_overload": {
                "kind": "gauge",
                "description": "1 while the head is shedding telemetry "
                               "(OFF→ON transition warn-logged; clears "
                               "when the fold queue drains)",
                "series": {tag: 1.0 if self._overload_alert else 0.0},
                "boundaries": None,
            },
            "ray_tpu_head_fold_queue_depth": {
                "kind": "gauge",
                "description": "telemetry events waiting in the head "
                               "fold queue",
                "series": {tag: float(len(self._fold_queue))},
                "boundaries": None,
            },
        }

    async def _on_head_stats(self, conn):
        """Control-plane load/health surface (`ray_tpu head`, dashboard
        /api/head): admission/fold-queue state, shed counter, overload
        alert, pubsub coalescing counters, and journal size/compaction
        — the numbers BENCH_head.json pins and operators watch at
        scale."""
        from ray_tpu._private import config

        journal = None
        if self.journal is not None:
            journal = {
                "path": self.journal.path,
                "size_bytes": self.journal.size_bytes,
                "floor_bytes": self._journal_floor,
                "compacting": bool(self._compacting),
                "last_compaction_ts": self._last_compaction_ts,
                "replayed_records": self._replayed_records,
                "replay_s": self._replay_s,
                "watermark_bytes": config.get(
                    "HEAD_SNAPSHOT_WATERMARK_BYTES"
                ),
            }
        return {
            "uptime_s": time.time() - self._started_ts,
            "nodes": len(self.nodes),
            "draining": len(self.draining),
            "slices": len(self.slices),
            "actors": len(self.actors),
            "subscriptions": {
                ch: len(s) for ch, s in self.subs.items() if s
            },
            "fold_queue_depth": len(self._fold_queue),
            "fold_queue_max": config.get("HEAD_FOLD_QUEUE_MAX"),
            "folded_total": self._folded_total,
            "shed_total": self._shed_total,
            "overload_alert": self._overload_alert,
            "pub_msgs_total": self._pub_msgs_total,
            "pub_pushes_total": self._pub_pushes_total,
            "journal": journal,
        }

    # ----------------------------------------------------------- health
    async def _remove_node(self, nid: str):
        """Declare a node dead: drop it from every table, fan collective
        member death out to surviving group members, and restart its
        actors within budget. Shared by the passive heartbeat reaper and
        the active collective probe."""
        node = self.nodes.pop(nid, None)
        if node is None:
            return
        if self.draining.pop(nid, None) is not None:
            # The drain completed in death; a journal replay must not
            # carry the tombstone forward.
            self._journal_append("drain", "del", {"node_id": nid})
        self._sched_drop_node(nid)
        conn = self._node_conns.pop(nid, None)
        if conn is not None:
            await conn.close()
        self.publish(
            "node",
            {"event": "removed", "node_id": nid, "addr": node["addr"]},
        )
        self._collective_member_died(node_addr=node["addr"])
        # Checkpoint chunks this node held are now under-replicated.
        self._schedule_ckpt_repair()
        # Slice fault domain: an UNEXPECTED member death implicates the
        # whole slice (preemption reaps hosts together; the stragglers
        # are seconds behind) — drain the siblings before they die with
        # work still on them. _slice_node_gone already moved the slice
        # to "dead" when this was the last host.
        gone = self._slice_node_gone(nid)
        if gone is not None:
            slice_id, rec = gone
            if rec["nodes"] and rec["state"] == "healthy":
                await self._maybe_drain_slice(
                    rec["nodes"][0],
                    f"slice {slice_id} host {nid[:12]}… died unexpectedly",
                )
        for aid, actor in self.actors.items():
            if actor["node_id"] == nid and actor["state"] == "ALIVE":
                # Node death goes through the same restart budget as
                # worker death (reference: actors on dead nodes are
                # rescheduled while max_restarts remains,
                # gcs_actor_manager).
                self._spawn_restart(aid, actor["addr"])

    async def _health_loop(self):
        """Mark nodes dead on heartbeat timeout (reference:
        gcs_health_check_manager.h:45 does active gRPC probes)."""
        from ray_tpu._private import config

        while True:
            await asyncio.sleep(
                min(5.0, config.get("HEALTH_TIMEOUT_S") / 3)
            )
            now = time.monotonic()
            # One batch section per reap tick: a correlated failure
            # (whole slice, whole rack) that times out together fans
            # out as one coalesced PUSH per channel per subscriber.
            with self._pub_batch():
                for nid, node in list(self.nodes.items()):
                    if (
                        now - node["last_seen"]
                        > config.get("HEALTH_TIMEOUT_S")
                    ):
                        await self._remove_node(nid)
            self._schedule_ckpt_repair()
