"""Head service: cluster-metadata authority (GCS equivalent).

Mirrors the reference's GCS server responsibilities (reference:
src/ray/gcs/gcs_server.h:100 — node table, actor registry, KV store,
pubsub, health checks, cluster-level scheduling) in one asyncio service.
State lives in process memory behind a tiny storage interface so a
Redis/file backend can slot in for fault tolerance (reference:
gcs/store_client/redis_store_client.h:126).
"""

from __future__ import annotations

import asyncio
import time
from typing import Any

from ray_tpu._private import rpc
from ray_tpu._private.ids import ActorID, NodeID

HEALTH_TIMEOUT_S = 30.0


class HeadService:
    def __init__(self):
        self.server = rpc.Server(self._handle)
        self.addr: str | None = None
        # node_id hex → {addr, resources, labels, last_seen, conn}
        self.nodes: dict[str, dict] = {}
        self.kv: dict[str, bytes] = {}
        # actor_id hex → {name, state, addr, node_id, class_name}
        self.actors: dict[str, dict] = {}
        self.named_actors: dict[str, str] = {}  # name → actor_id hex
        # channel → set[Connection]
        self.subs: dict[str, set[rpc.Connection]] = {}
        self._reaper: asyncio.Task | None = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        p = await self.server.start(host, port)
        self.addr = f"{host}:{p}"
        self._reaper = asyncio.ensure_future(self._health_loop())
        return self.addr

    async def stop(self):
        if self._reaper:
            self._reaper.cancel()
        await self.server.stop()

    # ------------------------------------------------------------ pubsub
    def publish(self, channel: str, msg: Any):
        for conn in list(self.subs.get(channel, ())):
            conn.push({"channel": channel, "msg": msg})

    # ----------------------------------------------------------- handler
    async def _handle(self, method: str, kw: dict, conn: rpc.Connection):
        fn = getattr(self, f"_on_{method}", None)
        if fn is None:
            raise rpc.RpcError(f"head: unknown method {method!r}")
        return await fn(conn=conn, **kw)

    async def _on_register_node(
        self, conn, node_id: str, addr: str, resources: dict, labels=None
    ):
        self.nodes[node_id] = {
            "addr": addr,
            "resources": dict(resources),
            "available": dict(resources),
            "labels": labels or {},
            "last_seen": time.monotonic(),
            "conn": conn,
        }
        conn.state["node_id"] = node_id
        self.publish("node", {"event": "added", "node_id": node_id, "addr": addr})
        return {"ok": True}

    async def _on_heartbeat(self, conn, node_id: str, available: dict):
        node = self.nodes.get(node_id)
        if node is None:
            return {"ok": False, "reregister": True}
        node["last_seen"] = time.monotonic()
        node["available"] = available
        return {"ok": True}

    async def _on_node_table(self, conn):
        return {
            nid: {k: v for k, v in n.items() if k != "conn"}
            for nid, n in self.nodes.items()
        }

    async def _on_pick_node(self, conn, resources: dict | None = None):
        """Cluster-level placement: pick a feasible node for a lease.

        Reference analogue: the hybrid scheduling policy's feasibility +
        availability scoring (reference:
        src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.h:25);
        centralized here (GCS-style) rather than spilled raylet-to-raylet.
        """
        resources = resources or {}
        best, best_score = None, None
        for nid, node in self.nodes.items():
            avail = node["available"]
            total = node["resources"]
            if any(total.get(k, 0) < v for k, v in resources.items()):
                continue  # infeasible
            free = sum(avail.get(k, 0) for k in resources) if resources else 1
            score = (
                all(avail.get(k, 0) >= v for k, v in resources.items()),
                free,
            )
            if best_score is None or score > best_score:
                best, best_score = nid, score
        if best is None:
            return {"ok": False, "error": "no feasible node"}
        return {"ok": True, "node_id": best, "addr": self.nodes[best]["addr"]}

    # ------------------------------------------------------------- kv
    async def _on_kv_put(self, conn, key: str, value: bytes, overwrite=True):
        if not overwrite and key in self.kv:
            return {"ok": False, "exists": True}
        self.kv[key] = value
        return {"ok": True}

    async def _on_kv_get(self, conn, key: str):
        return {"ok": key in self.kv, "value": self.kv.get(key)}

    async def _on_kv_del(self, conn, key: str):
        return {"ok": self.kv.pop(key, None) is not None}

    async def _on_kv_keys(self, conn, prefix: str = ""):
        return {"keys": [k for k in self.kv if k.startswith(prefix)]}

    # ----------------------------------------------------------- actors
    async def _on_register_actor(
        self,
        conn,
        actor_id: str,
        name: str | None,
        class_name: str,
        addr: str,
        node_id: str,
        detached: bool = False,
    ):
        if name:
            existing = self.named_actors.get(name)
            if existing and self.actors[existing]["state"] != "DEAD":
                return {"ok": False, "error": f"actor name {name!r} taken"}
            self.named_actors[name] = actor_id
        self.actors[actor_id] = {
            "name": name,
            "state": "ALIVE",
            "addr": addr,
            "node_id": node_id,
            "class_name": class_name,
            "detached": detached,
        }
        self.publish("actor", {"event": "alive", "actor_id": actor_id})
        return {"ok": True}

    async def _on_update_actor(self, conn, actor_id: str, state: str):
        actor = self.actors.get(actor_id)
        if actor is None:
            return {"ok": False}
        actor["state"] = state
        self.publish("actor", {"event": state.lower(), "actor_id": actor_id})
        return {"ok": True}

    async def _on_get_actor(
        self, conn, name: str | None = None, actor_id: str | None = None
    ):
        if name is not None:
            actor_id = self.named_actors.get(name)
        if actor_id is None or actor_id not in self.actors:
            return {"ok": False, "error": "actor not found"}
        return {"ok": True, "actor_id": actor_id, **self.actors[actor_id]}

    async def _on_list_actors(self, conn):
        return {"actors": dict(self.actors)}

    # ----------------------------------------------------------- pubsub
    async def _on_subscribe(self, conn, channel: str):
        self.subs.setdefault(channel, set()).add(conn)
        conn.state.setdefault("channels", []).append(channel)
        return {"ok": True}

    async def _on_publish(self, conn, channel: str, msg):
        self.publish(channel, msg)
        return {"ok": True}

    # ----------------------------------------------------------- health
    async def _health_loop(self):
        """Mark nodes dead on heartbeat timeout (reference:
        gcs_health_check_manager.h:45 does active gRPC probes)."""
        while True:
            await asyncio.sleep(5.0)
            now = time.monotonic()
            for nid, node in list(self.nodes.items()):
                if now - node["last_seen"] > HEALTH_TIMEOUT_S:
                    del self.nodes[nid]
                    self.publish(
                        "node", {"event": "removed", "node_id": nid}
                    )
                    for aid, actor in self.actors.items():
                        if actor["node_id"] == nid and actor["state"] == "ALIVE":
                            actor["state"] = "DEAD"
                            self.publish(
                                "actor", {"event": "dead", "actor_id": aid}
                            )
