"""Per-node dashboard agent: node-local HTTP observability endpoint.

(reference: python/ray/dashboard/agent.py — an aiohttp server on every
node serving node-local metrics, logs, and health directly, so the
dashboard/operators can inspect a node without routing through the
head. Here a minimal asyncio HTTP/1.1 GET server on the node daemon's
event loop; the agent address registers with the head as part of the
node record, and the dashboard links to it per node.)

Endpoints:
    /healthz         {node_id, addr, uptime_s, workers, leases}
    /api/stats       resources, store usage, spill/oom counters
    /api/logs        worker log listing (node-local files)
    /api/logs/<wid>  one worker's log (raw text, ?tail=N bytes)
    /api/train       per-job train goodput (head passthrough)
    /api/serve       per-deployment serve SLO ledger (head passthrough)
    /api/memory      per-node device-memory ledger (head passthrough)
    /api/checkpoints shard-store checkpoint table (head passthrough)
    /metrics         node-local Prometheus text
"""

from __future__ import annotations

import asyncio
import json
import time
import urllib.parse


class NodeAgent:
    def __init__(self, node):
        self.node = node  # NodeManager
        self._server: asyncio.AbstractServer | None = None
        self._t0 = time.monotonic()
        self.addr: str | None = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        self._server = await asyncio.start_server(self._conn, host, port)
        p = self._server.sockets[0].getsockname()[1]
        self.addr = f"{host}:{p}"
        return self.addr

    async def stop(self):
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # ---------------------------------------------------------- handlers
    def _healthz(self, query) -> dict:
        n = self.node
        return {
            "node_id": n.node_id,
            "addr": n.addr,
            "uptime_s": round(time.monotonic() - self._t0, 1),
            "workers": len(n.workers),
            "leases": len(n.leases),
            "draining": n.draining,
            "drain_info": n.drain_info,
            "ok": True,
        }

    def _stats(self, query) -> dict:
        n = self.node
        store = n._store()
        return {
            "node_id": n.node_id,
            "resources": n.total,
            "available": n.available,
            "pending_leases": len(n._pending),
            "store_used_bytes": store.used_bytes(),
            "store_capacity_bytes": getattr(store, "capacity_bytes", None),
            "spilled_bytes": n.spilled_bytes,
            "spilled_objects": n.spilled_objects,
            "oom_kills": n.oom_kills,
            "res_version": n._res_version,
            "draining": n.draining,
            "drain_info": n.drain_info,
        }

    async def _logs_list(self, query) -> list:
        n = self.node

        def scan():
            out = []
            if n.log_dir.is_dir():
                for path in sorted(n.log_dir.glob("worker-*.log")):
                    wid = path.name[len("worker-"):-len(".log")]
                    w = n.workers.get(wid)
                    out.append(
                        {
                            "worker_id": wid,
                            "size": path.stat().st_size,
                            "alive": bool(
                                w
                                and w.get("proc")
                                and w["proc"].poll() is None
                            ),
                        }
                    )
            return out

        # Off-loop like _log_text: a glob+stat sweep over a big log dir
        # on slow storage must not stall the scheduling loop.
        return await asyncio.to_thread(scan)

    async def _log_text(self, wid: str, query) -> str | None:
        """Seek+read off-loop: a multi-GB worker log must neither stall
        the node daemon's event loop (it also runs scheduling and the
        resource sync) nor be slurped into memory whole."""
        n = self.node
        tail = int(query.get("tail", ["0"])[0] or 0)
        cap = 16 * 1024 * 1024  # absolute response bound

        def read(path):
            with open(path, "rb") as f:
                f.seek(0, 2)
                size = f.tell()
                want = min(tail or size, cap)
                f.seek(max(0, size - want))
                return f.read(want)

        for path in n.log_dir.glob("worker-*.log"):
            if path.name[len("worker-"):-len(".log")].startswith(wid):
                data = await asyncio.to_thread(read, path)
                return data.decode("utf-8", "replace")
        return None

    async def _train(self, query) -> dict:
        """Head passthrough: per-job train goodput, answerable from any
        node's agent (operators probing a node don't need the driver
        dashboard up)."""
        if self.node.head is None:
            return {"error": "node has no head connection"}
        return await self.node.head.call("train_stats")

    async def _checkpoints(self, query) -> dict:
        """Head passthrough: shard-store checkpoint table (same data as
        the dashboard's /api/checkpoints)."""
        if self.node.head is None:
            return {"error": "node has no head connection"}
        run = query.get("run", [None])[0]
        return await self.node.head.call("ckpt_list", run=run)

    async def _serve(self, query) -> dict:
        """Head passthrough: per-deployment serve SLO ledger (same data
        as the dashboard's /api/serve)."""
        if self.node.head is None:
            return {"error": "node has no head connection"}
        return await self.node.head.call("serve_stats")

    async def _memory(self, query) -> dict:
        """Head passthrough: device-memory ledger (same data as the
        dashboard's /api/memory)."""
        if self.node.head is None:
            return {"error": "node has no head connection"}
        return await self.node.head.call("mem_stats")

    def _metrics(self, query) -> str:
        s = self._stats(query)
        lines = [
            "# TYPE ray_tpu_node_store_used_bytes gauge",
            f"ray_tpu_node_store_used_bytes {s['store_used_bytes']}",
            "# TYPE ray_tpu_node_workers gauge",
            f"ray_tpu_node_workers {len(self.node.workers)}",
            "# TYPE ray_tpu_node_leases gauge",
            f"ray_tpu_node_leases {len(self.node.leases)}",
            "# TYPE ray_tpu_node_spilled_bytes counter",
            f"ray_tpu_node_spilled_bytes {s['spilled_bytes']}",
            "# TYPE ray_tpu_node_oom_kills counter",
            f"ray_tpu_node_oom_kills {s['oom_kills']}",
            "# TYPE ray_tpu_node_draining gauge",
            f"ray_tpu_node_draining {int(self.node.draining)}",
        ]
        for k, v in self.node.available.items():
            lines.append(
                f'ray_tpu_node_available{{resource="{k}"}} {v}'
            )
        return "\n".join(lines) + "\n"

    # -------------------------------------------------------- http layer
    async def _conn(self, reader, writer):
        try:
            line = await reader.readline()
            if not line:
                return
            parts = line.decode("latin-1").split(" ")
            if len(parts) < 2 or parts[0] != "GET":
                await self._send(writer, 405, b"GET only")
                return
            while True:  # drain headers
                h = await reader.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
            parsed = urllib.parse.urlparse(parts[1])
            path = parsed.path
            query = urllib.parse.parse_qs(parsed.query)
            if path == "/healthz":
                body, ctype = json.dumps(self._healthz(query)), "application/json"
            elif path == "/api/stats":
                body, ctype = json.dumps(self._stats(query)), "application/json"
            elif path == "/api/logs":
                body, ctype = (
                    json.dumps(await self._logs_list(query)),
                    "application/json",
                )
            elif path.startswith("/api/logs/"):
                text = await self._log_text(path[len("/api/logs/"):], query)
                if text is None:
                    await self._send(writer, 404, b"no such worker log")
                    return
                body, ctype = text, "text/plain"
            elif path == "/api/train":
                body, ctype = (
                    json.dumps(await self._train(query)),
                    "application/json",
                )
            elif path == "/api/checkpoints":
                body, ctype = (
                    json.dumps(await self._checkpoints(query)),
                    "application/json",
                )
            elif path == "/api/serve":
                body, ctype = (
                    json.dumps(await self._serve(query)),
                    "application/json",
                )
            elif path == "/api/memory":
                body, ctype = (
                    json.dumps(await self._memory(query)),
                    "application/json",
                )
            elif path == "/metrics":
                body, ctype = self._metrics(query), "text/plain; version=0.0.4"
            else:
                await self._send(writer, 404, b"not found")
                return
            await self._send(
                writer, 200, body.encode(), ctype
            )
        except (ConnectionResetError, BrokenPipeError, asyncio.IncompleteReadError):
            pass
        # tpulint: allow(broad-except reason=not swallowed - the handler error is returned to the HTTP client as a 500 body)
        except Exception as e:
            try:
                await self._send(writer, 500, repr(e).encode())
            # tpulint: allow(broad-except reason=the client hung up before reading its 500; nobody is left to answer)
            except Exception:
                pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            # tpulint: allow(broad-except reason=socket teardown on an already-broken connection; nothing actionable)
            except Exception:
                pass

    @staticmethod
    async def _send(writer, status, body: bytes, ctype="text/plain"):
        writer.write(
            (
                f"HTTP/1.1 {status} X\r\nContent-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
            ).encode()
            + body
        )
        await writer.drain()
