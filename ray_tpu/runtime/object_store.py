"""Per-node shared-memory object store (plasma equivalent).

The reference runs a slab-allocated shared-memory daemon inside the raylet
(reference: src/ray/object_manager/plasma/store.h:55, dlmalloc pool,
fd-passing over unix sockets). TPU-native design note: on Linux, POSIX shm
*is* files under /dev/shm — so instead of a daemon brokering fds, each
sealed object is one mmap'd file in a session directory. Create-then-seal
is an atomic rename; readers mmap the sealed file and get zero-copy
memoryviews (pickle-5 out-of-band buffers point straight into the map).
Eviction/spilling hooks live here; a C++ pool allocator can replace the
file-per-object layout behind this same interface.

Layout of a sealed object file:
    [u64 magic][u64 inband_len][u32 n_buffers][u64 len * n_buffers]
    inband bytes, then each buffer 64-byte aligned.
"""

from __future__ import annotations

import mmap
import os
import struct
import tempfile
from pathlib import Path

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.serialization import Serialized

_MAGIC = 0x52545055_53544F52  # "RTPUSTOR"
_HEADER = struct.Struct("<QQI")
_LEN = struct.Struct("<Q")
_ALIGN = 64


def _aligned(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


class PlasmaView:
    """Zero-copy view of a sealed object; keeps its mmap alive."""

    __slots__ = ("inband", "buffers", "_map", "_file_size", "__weakref__")

    def __init__(self, mapping: mmap.mmap):
        self._map = mapping
        mv = memoryview(mapping)
        magic, inband_len, n_buffers = _HEADER.unpack_from(mv, 0)
        if magic != _MAGIC:
            raise ValueError("corrupt object store entry")
        off = _HEADER.size
        lens = []
        for _ in range(n_buffers):
            (length,) = _LEN.unpack_from(mv, off)
            lens.append(length)
            off += _LEN.size
        self.inband = mv[off : off + inband_len]
        off = _aligned(off + inband_len)
        self.buffers = []
        for length in lens:
            self.buffers.append(mv[off : off + length])
            off = _aligned(off + length)
        self._file_size = len(mv)


class ObjectStore:
    """One store per node; all processes on the node share the directory.

    Backend: the C++ shared-memory pool (ray_tpu/_native/shmstore.py —
    slab allocator + LRU eviction, the plasma equivalent) when the native
    toolchain is available; the file-per-object layout below is the
    fallback and also serves as the layout spec.
    """

    def __init__(self, directory: str | Path, capacity_bytes: int | None = None):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        # Weak cache of views handed out by this process (avoids
        # re-mmap / re-pin on repeat gets). Lifetime of the backing
        # memory is carried by the views themselves: file views keep
        # their mmap alive through the buffers' exporter chain, and pool
        # views attach the refcount pin to every exported buffer
        # (shmstore.PoolView), so a zero-copy deserialized value keeps
        # its block pinned exactly as long as the value is alive — and
        # no longer. A strong cache here would pin every object a
        # long-lived worker ever read, making the pool unspillable.
        import weakref

        self._views: "weakref.WeakValueDictionary[ObjectID, object]" = (
            weakref.WeakValueDictionary()
        )
        from ray_tpu._private import config

        self.pool = None
        if not config.get("DISABLE_NATIVE_STORE"):
            try:
                from ray_tpu._native.shmstore import ShmPool

                self.pool = ShmPool(
                    str(self.dir / "pool"), _pool_capacity(self.dir)
                )
            except Exception as e:  # noqa: BLE001 - fall back to file store
                import logging

                logger = logging.getLogger("ray_tpu")
                logger.warning(
                    "native shared-memory pool unavailable (%s: %s); "
                    "falling back to the file-per-object store",
                    type(e).__name__,
                    e,
                )
                self.pool = None
        # Spill directory on DISK (shm is RAM): cold objects move here
        # under memory pressure and are served back transparently
        # (reference: LocalObjectManager spills to external storage via
        # io workers, local_object_manager.h:44). Every process of the
        # session derives the same path from the store dir name.
        self.spill_dir = Path(
            config.get("SPILL_DIR")
            or os.path.join(
                tempfile.gettempdir(), f"{self.dir.name}-spill"
            )
        )
        self.capacity_bytes = (
            capacity_bytes
            or (self.pool.capacity_bytes() if self.pool is not None else 0)
            or _pool_capacity(self.dir)
        )

    def _path(self, object_id: ObjectID) -> Path:
        return self.dir / object_id.hex()

    def put(self, object_id: ObjectID, data: Serialized) -> int:
        """Create + seal in one step. Returns bytes written."""
        if self.pool is not None:
            try:
                return self.pool.put(
                    object_id.binary(), data.materialize_buffers()
                )
            except MemoryError:
                pass  # over-capacity object: fall through to a file
        path = self._path(object_id)
        if path.exists():
            return path.stat().st_size  # immutable: double-put is a no-op
        return _write_object_file(path, data.inband, data.buffers)

    def get(self, object_id: ObjectID):
        view = self._views.get(object_id)
        if view is not None:
            return view
        if self.pool is not None:
            pv = self.pool.get(object_id.binary())
            if pv is not None:
                self._views[object_id] = pv
                return pv
        view = self._map_file(self._path(object_id))
        if view is None:
            # Spilled to disk: serve from the spill file (mmap'd; the
            # page cache amortizes repeat reads). Reference restores to
            # plasma via io workers, local_object_manager.h:44.
            view = self._map_file(self._spill_path(object_id))
        if view is not None:
            self._views[object_id] = view
        return view

    def _map_file(self, path: Path):
        try:
            fd = os.open(path, os.O_RDONLY)
        except FileNotFoundError:
            return None
        try:
            size = os.fstat(fd).st_size
            mapping = mmap.mmap(fd, size, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        return PlasmaView(mapping)

    def release(self, object_id: ObjectID) -> None:
        """Drop this process's cached mmap view (serving paths that touch
        many objects must not pin every mapping forever)."""
        self._views.pop(object_id, None)

    def contains(self, object_id: ObjectID) -> bool:
        if object_id in self._views or self._path(object_id).exists():
            return True
        if self.pool is not None and self.pool.contains(object_id.binary()):
            return True
        return self._spill_path(object_id).exists()

    def delete(self, object_id: ObjectID) -> None:
        self._views.pop(object_id, None)
        if self.pool is not None:
            self.pool.delete(object_id.binary())
        for path in (self._path(object_id), self._spill_path(object_id)):
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass

    # ---------------------------------------------------------- spilling
    def _spill_path(self, object_id: ObjectID) -> Path:
        return self.spill_dir / object_id.hex()

    def spill_candidates(self) -> list[tuple[ObjectID, int, float]]:
        """(object_id, size, lru_key) for spillable objects, coldest
        first. Pool objects rank by the pool's LRU tick; file-backed
        objects by mtime (both orderings are per-source; the merged list
        interleaves them, which is fine for a watermark loop)."""
        out = []
        if self.pool is not None:
            for id_bytes, size, lru in self.pool.scan():
                try:
                    out.append((ObjectID(id_bytes), size, float(lru)))
                except ValueError:
                    continue
            out.sort(key=lambda t: t[2])
        files = []
        for name, size in self.list_objects():
            try:
                oid = ObjectID.from_hex(name)
            except ValueError:
                continue
            try:
                mtime = self._path(oid).stat().st_mtime
            except OSError:
                continue
            files.append((oid, size, mtime))
        files.sort(key=lambda t: t[2])
        # Pool ticks and mtimes are different clocks: each group is
        # coldest-first internally; pool entries go first (they are the
        # allocator under pressure), file entries after.
        return out + files

    def spill_one(self, object_id: ObjectID) -> int:
        """Move one sealed object to the disk spill dir. Returns shm
        bytes freed (0 if the object was busy or already gone)."""
        spill_path = self._spill_path(object_id)
        if spill_path.exists():
            freed = self._drop_shm_copy(object_id)
            return freed
        shm_path = self._path(object_id)
        if shm_path.exists():
            # File-backed: copy to a temp name, atomic-rename into the
            # spill dir, then drop the shm copy. Readers racing this see
            # either copy (both sealed + immutable).
            self.spill_dir.mkdir(parents=True, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self.spill_dir, prefix=".spill-")
            try:
                with os.fdopen(fd, "wb") as dst, open(shm_path, "rb") as src:
                    import shutil

                    shutil.copyfileobj(src, dst)
                os.rename(tmp, spill_path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            return self._drop_shm_copy(object_id)
        if self.pool is not None:
            view = self.pool.get(object_id.binary())
            if view is None:
                return 0
            self.spill_dir.mkdir(parents=True, exist_ok=True)
            _write_object_file(spill_path, view.inband, view.buffers)
            del view  # release the pool pin before deleting
            # Report what was ACTUALLY freed: a reader pinning the
            # object between scan and delete leaves the shm copy in
            # place (the spill file is a harmless duplicate) — the next
            # watermark tick retries.
            return self._drop_shm_copy(object_id)
        return 0

    def _drop_shm_copy(self, object_id: ObjectID) -> int:
        """Remove the shm copy of an object that has a spill file."""
        freed = 0
        if self.pool is not None and self.pool.contains(object_id.binary()):
            before = self.pool.used_bytes()
            self.pool.delete(object_id.binary())
            freed = max(0, before - self.pool.used_bytes())
        path = self._path(object_id)
        try:
            size = path.stat().st_size
            os.unlink(path)
            freed += size
        except OSError:
            pass
        # A stale read-only view in THIS process keeps serving safely
        # (unlinked files stay mapped), but drop it so memory frees.
        self._views.pop(object_id, None)
        return freed

    def iter_ids(self) -> list[ObjectID]:
        """Every object resident in this store — pool, file-backed, and
        spilled copies. This is the drain-evacuation sweep's work list:
        anything here is a primary some consumer may still resolve to."""
        seen: set[ObjectID] = set()
        if self.pool is not None:
            for id_bytes, _size, _lru in self.pool.scan():
                try:
                    seen.add(ObjectID(id_bytes))
                except ValueError:
                    continue
        for name, _size in self.list_objects():
            try:
                seen.add(ObjectID.from_hex(name))
            except ValueError:
                continue
        if self.spill_dir.exists():
            for p in self.spill_dir.iterdir():
                try:
                    seen.add(ObjectID.from_hex(p.name))
                except ValueError:
                    continue
        return sorted(seen, key=lambda o: o.hex())

    def list_objects(self) -> list[tuple[str, int]]:
        """(object_id hex, size) pairs. Best-effort: covers the
        file-backed objects; the native pool does not expose a scan."""
        out = []
        for p in self.dir.iterdir():
            # Skip the pool file and in-flight temp files from concurrent
            # put()s; an entry may also vanish between iterdir and stat.
            if not all(c in "0123456789abcdef" for c in p.name):
                continue
            try:
                if p.is_file():
                    out.append((p.name, p.stat().st_size))
            except OSError:
                continue
        return out

    def used_bytes(self) -> int:
        pool = self.pool.used_bytes() if self.pool is not None else 0
        return pool + sum(
            p.stat().st_size
            for p in self.dir.iterdir()
            if p.is_file() and p.name != "pool"
        )

    def destroy(self) -> None:
        self._views.clear()
        if self.pool is not None:
            self.pool.destroy()
        import shutil

        shutil.rmtree(self.dir, ignore_errors=True)
        shutil.rmtree(self.spill_dir, ignore_errors=True)


def _write_object_file(path: Path, inband, buffers) -> int:
    """Write the sealed-object file layout (header + inband + aligned
    buffers) with create-then-atomic-rename sealing. Returns total bytes."""
    header = _HEADER.pack(_MAGIC, len(inband), len(buffers))
    lens = b"".join(_LEN.pack(len(b)) for b in buffers)
    meta_len = len(header) + len(lens)

    total = _aligned(meta_len + len(inband))
    for b in buffers:
        total = _aligned(total + len(b))
    total = max(total, 1)

    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=".create-")
    try:
        os.ftruncate(fd, total)
        with mmap.mmap(fd, total) as m:
            m[: len(header)] = header
            off = len(header)
            m[off : off + len(lens)] = lens
            off += len(lens)
            m[off : off + len(inband)] = bytes(inband)
            off = _aligned(off + len(inband))
            for b in buffers:
                m[off : off + len(b)] = (
                    b if isinstance(b, (bytes, memoryview)) else bytes(b)
                )
                off = _aligned(off + len(b))
        os.close(fd)
        os.rename(tmp, path)  # seal
    except BaseException:
        os.close(fd) if fd >= 0 else None
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return total


def segment_meta(view) -> dict:
    """Segment layout of a serialized object view (chunked-pull meta)."""
    seg_lens = [len(view.inband)] + [len(b) for b in view.buffers]
    return {"ok": True, "seg_lens": seg_lens, "total": sum(seg_lens)}


def segment_window(view, offset: int, size: int) -> bytes:
    """One window of the logical byte stream (inband ++ buffers), sliced
    without copying the parts outside the window."""
    out = bytearray()
    pos = 0
    for seg in [view.inband, *view.buffers]:
        seg_len = len(seg)
        if offset < pos + seg_len and len(out) < size:
            start = max(0, offset - pos)
            take = min(seg_len - start, size - len(out))
            out += memoryview(seg)[start : start + take]
        pos += seg_len
        if len(out) >= size:
            break
    return bytes(out)


def _pool_capacity(directory: Path) -> int:
    from ray_tpu._private import config

    override = config.get("POOL_BYTES")
    if override:
        return int(override)
    try:
        st = os.statvfs(directory)
        free = st.f_bavail * st.f_frsize
    except OSError:
        free = 4 << 30
    # Reference sizes plasma at 30% of system memory by default
    # (ray_config_def.h object_store defaults); cap at 2 GiB here.
    return max(64 << 20, min(2 << 30, int(free * 0.3)))


def default_store_dir(session: str) -> str:
    base = "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()
    return os.path.join(base, f"ray_tpu-{session}")
