"""Core worker: in-process runtime for every driver and worker process.

Mirrors the reference core worker (reference:
src/ray/core_worker/core_worker.h:167): task submission with leased
workers (normal_task_submitter.h:86), ordered actor-task submission
(actor_task_submitter.h:68), an in-memory store for small results owned by
the submitting process (memory_store.h:47), shared-memory store access for
large objects, task retries on worker death (task_manager.h:175), and the
task-execution callback on the worker side (task_receiver.h:43 /
_raylet.pyx:1602 execute_task).

Ownership model: the process that submits a task (or calls put) owns the
returned objects — it holds their values (inline) or locations (store) and
serves `get_object` to any process holding the ref. This is the
reference's ownership design (SURVEY.md section 5, failure detection row).
"""

from __future__ import annotations

import asyncio
import collections
import functools
import hashlib
import inspect
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Sequence

from ray_tpu._private import config, rpc
from ray_tpu._private.ids import ActorID, FunctionID, ObjectID, TaskID
from ray_tpu._private.serialization import Serialized, deserialize, serialize
from ray_tpu.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    RayTaskError,
    TaskCancelledError,
    WorkerDiedError,
)
from ray_tpu.runtime.object_store import ObjectStore

import logging

logger = logging.getLogger("ray_tpu.core_worker")

INLINE_MAX_BYTES = 100_000
DEFAULT_RETRIES = 3
GENERATOR_BACKPRESSURE_ITEMS = 8  # max undelivered items per stream


def _spec_nbytes(spec: dict) -> int:
    """Approximate retained size of a lineage entry: the serialized args
    dominate (by-value entries carry inband bytes + buffers)."""
    total = 256  # envelope
    for entry in spec.get("args", ()):
        if entry[1] == "val":
            total += len(entry[2]) + sum(len(b) for b in entry[3])
        else:
            total += 64
    return total


class _NeedsPull(Exception):
    """Internal: the record's bytes live in another node's store."""

    def __init__(self, holder_addr: str):
        super().__init__(holder_addr)
        self.holder_addr = holder_addr


class _NeedsTensor(Exception):
    """Internal: the record's payload lives in a worker's device-tensor
    store (tensor transport) — fetch it from the source actor."""

    def __init__(self, meta: dict):
        super().__init__(meta)
        self.meta = meta


class CoreWorker:
    def __init__(
        self,
        mode: str,  # "driver" | "worker" | "client"
        head_addr: str,
        node_addr: str,
        store_dir: str,
        worker_id: str | None = None,
    ):
        # "client": a remote driver outside the cluster (reference: Ray
        # Client, python/ray/util/client/) — no local node daemon, so
        # leases always go through the head and large puts upload to an
        # anchor node whose store serves the cluster.
        self.mode = mode
        self.head_addr = head_addr
        self.node_addr = node_addr
        self.store = ObjectStore(store_dir)
        self.worker_id = worker_id
        self.addr: str | None = None  # own serve addr (ownership identity)
        self.server = rpc.Server(self._handle)
        self.head: rpc.Connection | None = None
        self.node: rpc.Connection | None = None
        self._conns: dict[str, rpc.Connection] = {}
        self._conn_locks: dict[str, asyncio.Lock] = {}

        # memory store: oid hex → ("value", inband, buffers) | ("error", e)
        # | ("in_store", holder_node_addr | None) — the holder addr names
        # the node whose store has the bytes (multi-node pulls)
        self.memory: dict[str, tuple] = {}
        self._waiters: dict[str, list[asyncio.Future]] = {}
        # Object directory for objects this worker owns: oid hex → node
        # addrs holding a store copy beyond the primary (pullers register
        # after caching; reference: ownership_object_directory.h location
        # updates). Lets later pulls stripe across many sources.
        self._locations: dict[str, set] = {}
        # Drain-time evacuation watch (armed on first in_store record).
        self._drain_evac_armed = False

        # function table
        self._exported: dict[int, str] = {}  # id(fn) → fn_id hex
        self._fn_cache: dict[str, Any] = {}  # fn_id hex → callable/class

        # Lease pools: sched key → {"free": [(lease, idle_since)],
        # "waiters": deque[Future], "inflight": int}. A finished task's
        # lease is handed straight to the next queued task of the same
        # scheduling class — no node round-trip on the steady-state path
        # (reference: normal_task_submitter.h lease caching + pipelined
        # lease requests, ClusterSizeBasedLeaseRequestRateLimiter :74);
        # free leases return to the node after an idle timeout so they
        # don't pin resources (ReturnWorkerLease).
        self._lease_pools: dict[tuple, dict] = {}
        self._lease_cap = 8              # max parked free leases per key
        self._max_inflight_leases = 16   # max pending lease requests per key
        self._lease_idle_s = 1.0
        self._lease_reaper: asyncio.Task | None = None

        # worker-side execution
        self._exec_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ray_tpu_exec"
        )
        self._exec_queue: asyncio.Queue | None = None
        self._exec_task: asyncio.Task | None = None
        self._actor_instance: Any = None
        self._actor_id: str | None = None
        # Async (coroutine) actor methods run concurrently, out of order,
        # bounded by max_concurrency (reference: asyncio actors via
        # OutOfOrderActorSchedulingQueue + ConcurrencyGroupManager fibers,
        # core_worker/task_execution/fiber.h).
        self._async_sema = asyncio.Semaphore(100)

        self._put_index = 0
        self._root_task = TaskID.random()
        self._anchor: tuple[str, rpc.Connection] | None = None  # client mode

        # actor_id → freshest known address (updated on head-driven
        # restarts; handles carry the birth address only).
        self._actor_addrs: dict[str, str] = {}

        # Streaming generator tasks this process owns: task_id → queue of
        # ("item", oid_hex) | ("error", exc) | ("done",); plus a count of
        # items delivered so far (gates retries: only an undelivered
        # stream may be resubmitted).
        self._generators: dict[str, asyncio.Queue] = {}
        self._gen_delivered: dict[str, int] = {}
        # task_id → current submission attempt: reports from a PREVIOUS
        # attempt (a worker that died after sending but before we saw the
        # item) are rejected, so a retried stream can never deliver
        # duplicates.
        self._gen_attempt: dict[str, int] = {}

        # Device-tensor store (reference: gpu_object_store.py in
        # python/ray/experimental/gpu_object_manager/): values returned
        # by tensor-transport actor methods stay HERE, in the producing
        # worker, on device; only metadata travels through the normal
        # result path. Other actors fetch the payload point-to-point
        # (collective send/recv when a shared group exists, direct rpc
        # otherwise) — never through the host object store.
        self.tensor_store: dict[str, Any] = {}
        # Received-tensor LRU (consumer side): repeat gets of the same
        # tensor ref hit this instead of re-transferring the payload
        # (reference: gpu_object_store caches received tensors).
        self._tensor_cache: collections.OrderedDict[str, Any] = (
            collections.OrderedDict()
        )
        self._tensor_cache_cap = 64
        # Producer-side export buffers for chunked tensor fetches:
        # token → (serialized blob segments, total, created_at).
        self._tensor_exports: dict[str, tuple] = {}

        # Lineage: task_id → resubmit info for normal-task returns, so a
        # lost store object can be reconstructed by re-executing its
        # creating task (reference: ObjectRecoveryManager
        # object_recovery_manager.h:41 + TaskManager lineage,
        # task_manager.h:175). Bounded FIFO: oldest lineage is dropped
        # first (its objects then fail as unreconstructable, like the
        # reference under lineage eviction).
        self._lineage: collections.OrderedDict[str, dict] = (
            collections.OrderedDict()
        )
        self._lineage_cap = 16384
        # Entry count alone is not enough: each entry retains the full
        # serialized args, so lineage is ALSO evicted on a byte budget
        # (reference: RAY_max_lineage_bytes-style eviction in
        # task_manager.h:175).
        self._lineage_bytes = 0
        self._oid_to_task: dict[str, str] = {}
        # task_id → in-flight reconstruction future (dedupe).
        self._reconstructing: dict[str, asyncio.Future] = {}

        # Cancellation state for normal tasks this process drives:
        # task_id → {"cancelled": bool, "lease": current lease | None}
        # (reference: CoreWorker::CancelTask — queued tasks fail fast,
        # running ones are force-killed at the worker).
        self._cancel_state: dict[str, dict] = {}

        # Task-event buffer, flushed to the head periodically (reference:
        # worker-side TaskEventBuffer core_worker/task_event_buffer.h →
        # GcsTaskManager). Bounded: observability must not OOM the worker.
        self._task_events: list[dict] = []
        self._event_flusher: asyncio.Task | None = None

        # Extension RPC handlers (collective groups, channels, ...):
        # name → async fn(conn=..., **kw). Checked before built-ins.
        self.ext_handlers: dict[str, Any] = {}
        # Head pubsub: channel → sync callback(msg). Populated via
        # subscribe(); re-issued on head reconnect.
        self._push_handlers: dict[str, Any] = {}

    # ----------------------------------------------------------- startup
    async def start(self, host: str = "127.0.0.1") -> str:
        port = await self.server.start(host, 0)
        self.addr = f"{host}:{port}"
        # Reconnecting head client: a head restart is transparent to
        # drivers/workers (idempotent queries retry across the outage;
        # reference: RetryableGrpcClient wrapping the gcs client).
        # Subscriptions re-issue on reconnect — the restarted head's
        # subscriber table starts empty (reference: resubscribe on
        # NotifyGCSRestart).
        self.head = await rpc.ReconnectingClient(
            self.head_addr,
            on_push=self._on_head_push,
            on_reconnect=self._resubscribe,
            reconnect_timeout=config.get("HEAD_RECONNECT_S"),
        ).connect()
        # Observer connections (read-only CLI/dashboard) have no local
        # node: head queries and object reads work, task submission does
        # not.
        if self.node_addr:
            self.node = await rpc.connect(self.node_addr)
        self._exec_queue = asyncio.Queue()
        self._exec_task = asyncio.ensure_future(self._exec_loop())
        self._lease_reaper = asyncio.ensure_future(self._lease_reap_loop())
        self._event_flusher = asyncio.ensure_future(self._flush_events_loop())
        return self.addr

    def _on_head_push(self, payload):
        """PUSH frame from the head (pubsub delivery). A "batch" frame
        carries a whole coalesced tick of messages in publish order
        (the head batches mass-death/drain fan-out); handlers still see
        one message at a time."""
        try:
            handler = self._push_handlers.get(payload.get("channel"))
            if handler is None:
                return
            if "batch" in payload:
                for msg in payload["batch"]:
                    handler(msg)
            else:
                handler(payload.get("msg"))
        except Exception:  # noqa: BLE001 - a bad handler must not kill recv
            logger.warning(
                "pubsub handler for channel %r raised",
                payload.get("channel"), exc_info=True,
            )

    async def subscribe(self, channel: str, handler) -> None:
        """Subscribe to a head pubsub channel; `handler(msg)` runs on the
        runtime loop for each delivery. Survives head restarts."""
        self._push_handlers[channel] = handler
        await self.head.call("subscribe", channel=channel)

    async def _resubscribe(self, conn) -> None:
        for channel in self._push_handlers:
            await conn.call("subscribe", channel=channel)

    async def stop(self):
        if self._exec_task:
            self._exec_task.cancel()
        if self._lease_reaper:
            self._lease_reaper.cancel()
        if self._event_flusher:
            self._event_flusher.cancel()
            await self._flush_events()  # final drain
        self._exec_pool.shutdown(wait=False, cancel_futures=True)
        for conn in list(self._conns.values()):
            await conn.close()
        if self.head:
            await self.head.close()
        if self.node:
            await self.node.close()
        await self.server.stop()

    async def _connect(self, addr: str, retries: int = 3) -> rpc.Connection:
        conn = self._conns.get(addr)
        if conn is not None and not conn._closed:
            return conn
        from ray_tpu._private.sanitize import maybe_async_lock

        lock = self._conn_locks.setdefault(
            addr, maybe_async_lock(f"core_worker.conn.{addr}"))
        async with lock:
            conn = self._conns.get(addr)
            if conn is not None and not conn._closed:
                return conn
            conn = await rpc.connect(addr, retries=retries)
            self._conns[addr] = conn
            return conn

    # ---------------------------------------------------- function table
    async def export_function(self, fn: Any) -> str:
        key = id(fn)
        fn_id = self._exported.get(key)
        if fn_id is not None:
            return fn_id
        blob = serialize(fn).materialize_buffers()
        data = blob.inband + b"".join(blob.buffers)
        fn_id = hashlib.sha1(data).hexdigest()[: FunctionID.LENGTH * 2]
        await self.head.call(
            "kv_put", key=f"fn:{fn_id}", value=data, overwrite=True
        )
        self._exported[key] = fn_id
        self._fn_cache[fn_id] = fn
        return fn_id

    async def _fetch_function(self, fn_id: str) -> Any:
        # "xfn:<name>" = cross-language registry entry (_private/xlang
        # register_function): the id IS the KV key, named by the
        # registrar rather than content-hashed — and therefore MUTABLE
        # (re-register/unregister), so never cached: a pooled worker
        # must not keep executing a stale implementation.
        if fn_id.startswith("xfn:"):
            reply = await self.head.call("kv_get", key=fn_id)
            if not reply["ok"]:
                raise RayTaskError(
                    f"cross-language function {fn_id[4:]!r} is not "
                    "registered"
                )
            return deserialize(reply["value"])
        fn = self._fn_cache.get(fn_id)
        if fn is not None:
            return fn
        reply = await self.head.call("kv_get", key=f"fn:{fn_id}")
        if not reply["ok"]:
            raise RayTaskError(f"function {fn_id} not found in cluster KV")
        fn = deserialize(reply["value"])
        self._fn_cache[fn_id] = fn
        return fn

    # ------------------------------------------------------------- args
    def _encode_args(self, args: Sequence, kwargs: dict) -> list:
        """Top-level ObjectRef args go by-ref; everything else by value
        (reference: LocalDependencyResolver dependency_resolver.h:36)."""
        from ray_tpu.api import ObjectRef

        encoded = []
        for slot, value in [(None, a) for a in args] + list(kwargs.items()):
            if isinstance(value, ObjectRef):
                encoded.append((slot, "ref", value.hex, value.owner_addr))
            else:
                s = serialize(value).materialize_buffers()
                encoded.append((slot, "val", s.inband, s.buffers))
        return encoded

    def _encode_args_mp(self, args: Sequence, kwargs: dict) -> list:
        """Cross-language args: plain msgpack only (numbers, strings,
        bytes, lists, maps) — a foreign worker cannot unpickle, and
        refs would need an owner protocol it does not speak."""
        if kwargs:
            raise TypeError(
                "cross-language calls take positional arguments only"
            )
        encoded = []
        for value in args:
            try:
                encoded.append((None, "mp", rpc.pack_frame(value)))
            except (TypeError, ValueError) as e:
                raise TypeError(
                    "cross-language arguments must be msgpack-encodable "
                    f"plain data: {e}"
                ) from None
        return encoded

    async def _decode_args(self, encoded: list) -> tuple[list, dict]:
        args, kwargs = [], {}
        for entry in encoded:
            slot = entry[0]
            if entry[1] == "ref":
                value = await self._get_one(entry[2], entry[3], timeout=None)
            elif entry[1] == "mp":
                # Cross-language caller: plain msgpack data, never
                # pickle (reference: cross-language serialization).
                value = rpc.unpack_frame(entry[2])
            else:
                value = deserialize(entry[2], entry[3])
            if slot is None:
                args.append(value)
            else:
                kwargs[slot] = value
        return args, kwargs

    # ------------------------------------------------------ memory store
    def _store_result(self, oid_hex: str, record: tuple):
        self.memory[oid_hex] = record
        if record and record[0] == "in_store":
            # Store-resident bytes can sit on a node that later drains:
            # start watching drain fan-out the first time we own one, so
            # we can push sole copies to a healthy peer before the node
            # retires (reference: the raylet's spill-before-exit path).
            self._arm_drain_evacuation()
        for fut in self._waiters.pop(oid_hex, []):
            if not fut.done():
                fut.set_result(None)

    async def _wait_local(self, oid_hex: str, timeout: float | None):
        if oid_hex in self.memory:
            return
        fut = asyncio.get_running_loop().create_future()
        self._waiters.setdefault(oid_hex, []).append(fut)
        try:
            await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            raise GetTimeoutError(f"timed out waiting for {oid_hex[:12]}…")

    def _read_record(self, oid_hex: str):
        """memory-store record → python value (may raise stored error)."""
        kind, *rest = self.memory[oid_hex]
        if kind == "error":
            raise rest[0]
        if kind == "value":
            return deserialize(rest[0], rest[1])
        if kind == "in_store":
            view = self.store.get(ObjectID.from_hex(oid_hex))
            if view is not None:
                return deserialize(view.inband, view.buffers)
            # Not in THIS node's store: the record may carry the holding
            # node's address (multi-node result) — callers in async
            # context pull it chunked via _maybe_pull_record.
            holder = rest[0] if rest else None
            if holder:
                raise _NeedsPull(holder)
            raise ObjectLostError(f"object {oid_hex[:12]}… lost from store")
        if kind == "tensor":
            if oid_hex in self.tensor_store:  # reading our own tensor
                return self.tensor_store[oid_hex]
            if oid_hex in self._tensor_cache:  # previously fetched
                self._tensor_cache.move_to_end(oid_hex)
                return self._tensor_cache[oid_hex]
            raise _NeedsTensor(rest[0])
        raise AssertionError(kind)

    @staticmethod
    def _deadline_of(timeout: float | None, what: str):
        """One deadline for a whole multi-stage read: returns a
        ``remaining()`` closure that yields the leftover budget and
        raises GetTimeoutError once it is spent."""
        loop = asyncio.get_running_loop()
        deadline = None if timeout is None else loop.time() + timeout

        def remaining():
            if deadline is None:
                return None
            left = deadline - loop.time()
            if left <= 0:
                raise GetTimeoutError(f"timed out on {what}")
            return left

        return remaining

    async def _maybe_pull_record(self, oid_hex: str, timeout=None):
        """_read_record + transparent chunked pull for remote-store
        records (reference: raylet PullManager drives chunked Push from
        the holding node, pull_manager.h:50). A lost object (holder node
        dead, store copy evicted) triggers lineage reconstruction: the
        creating task is re-executed and the read retried (reference:
        ObjectRecoveryManager object_recovery_manager.h:41). ``timeout``
        bounds the WHOLE sequence (pulls + reconstructions)."""
        remaining = self._deadline_of(timeout, f"object {oid_hex[:12]}…")
        while True:
            try:
                return self._read_record(oid_hex)
            except _NeedsTensor as need:
                return await self._fetch_tensor(
                    oid_hex, need.meta, remaining()
                )
            except _NeedsPull as need:
                try:
                    from ray_tpu.runtime import transfer

                    conns, addr_of = await transfer.connect_sources(
                        self._locations.get(oid_hex),
                        need.holder_addr,
                        self.node_addr,
                        lambda a: self._connect(a, retries=1),
                    )
                    return await self._pull_remote(
                        ObjectID.from_hex(oid_hex),
                        conns,
                        None,
                        remaining(),
                        addr_of,
                    )
                except GetTimeoutError:
                    raise
                except (rpc.ConnectionLost, rpc.RpcError, ObjectLostError) as e:
                    if not await self._reconstruct(oid_hex, remaining()):
                        hit = await self._remote_tier_fetch(oid_hex)
                        if hit is not None:
                            return hit[1]
                        raise ObjectLostError(
                            f"object {oid_hex[:12]}… lost (holder "
                            f"{need.holder_addr} unreachable) and not "
                            f"reconstructable: {e}"
                        ) from e
            except ObjectLostError:
                if not await self._reconstruct(oid_hex, remaining()):
                    hit = await self._remote_tier_fetch(oid_hex)
                    if hit is not None:
                        return hit[1]
                    raise

    # ------------------------------------------- drain-time evacuation
    def _arm_drain_evacuation(self) -> None:
        """Idempotently subscribe to drain fan-out (via the collective
        death watch — pubsub allows one handler per channel, so drain
        notices reach us through drain.add_listener, not a second
        subscription)."""
        if self._drain_evac_armed or not config.get(
            "OBJECT_DRAIN_EVACUATION"
        ):
            return
        if self.head is None or self.mode == "client":
            return  # client drivers can't pull from node stores anyway
        self._drain_evac_armed = True
        from ray_tpu.runtime import drain

        drain.add_listener(self._on_drain_notice)
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        from ray_tpu import collective as _coll

        t = loop.create_task(_coll._ensure_death_watch(self))
        t.add_done_callback(lambda t: t.exception())

    def _on_drain_notice(self, notice: dict) -> None:
        """drain.record() callback (sync, runs in the pubsub handler):
        schedule the actual evacuation on the loop."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        t = loop.create_task(self._evacuate_for_drain(notice))
        t.add_done_callback(lambda t: t.exception())

    async def _evacuate_for_drain(self, notice: dict) -> None:
        """Push owned objects whose ONLY copies live on the draining
        node to a healthy peer (or, with no peer, to the remote spill
        tier) while the node is still alive to serve pulls. Without
        this, every sole-copy object on the node becomes a lineage
        reconstruction — or a loss — the moment it retires."""
        drain_addr = notice.get("node_addr")
        if not drain_addr or self.head is None:
            return
        victims: list[str] = []
        for oid_hex, rec in list(self.memory.items()):
            if not rec or rec[0] != "in_store":
                continue
            primary = rec[1] if len(rec) > 1 else None
            locs = set(self._locations.get(oid_hex) or ())
            locs.add(primary or self.node_addr)
            locs.discard(None)
            if locs and locs <= {drain_addr}:
                victims.append(oid_hex)
        if not victims:
            return
        from ray_tpu.runtime.drain import EVACUATED

        try:
            status = await self.head.call("cluster_status")
        except (rpc.ConnectionLost, rpc.RpcError):
            return
        draining = set(status.get("draining") or {})
        peers = [
            n["addr"]
            for nid, n in sorted((status.get("nodes") or {}).items())
            if n.get("addr")
            and n["addr"] != drain_addr
            and nid not in draining
        ]
        if peers:
            try:
                peer_addr = peers[0]
                peer = await self._connect(peer_addr, retries=1)
                reply = await peer.call(
                    "prefetch_objects", oids=victims, owner_addr=self.addr
                )
            except (rpc.ConnectionLost, rpc.RpcError) as e:
                EVACUATED.inc(len(victims), tags={"outcome": "failed"})
                logger.warning(
                    "drain evacuation to peer %s failed: %s", peers[0], e
                )
                return
            results = reply.get("results") or {}
            for oid_hex in victims:
                if results.get(oid_hex):
                    self._locations.setdefault(oid_hex, set()).add(
                        peer_addr
                    )
                    rec = self.memory.get(oid_hex)
                    if rec and rec[0] == "in_store":
                        # Re-point the primary off the doomed node so
                        # reads never even try it post-retirement. A
                        # holder-less record means OUR node's store —
                        # which is the one draining, or the object
                        # wouldn't be a victim.
                        self.memory[oid_hex] = ("in_store", peer_addr)
                    self._locations[oid_hex].discard(drain_addr)
                    EVACUATED.inc(1, tags={"outcome": "peer"})
                else:
                    EVACUATED.inc(1, tags={"outcome": "failed"})
            return
        # No healthy peer: spill to the remote tier (the node-side
        # sweep covers objects in ITS store; this covers records whose
        # holder is the draining node but we own the directory entry).
        from ray_tpu.checkpoint import remote as _remote

        tier = _remote.get_tier()
        if tier is None:
            EVACUATED.inc(len(victims), tags={"outcome": "failed"})
            return
        from ray_tpu.runtime import transfer

        for oid_hex in victims:
            try:
                conn = await self._connect(drain_addr, retries=1)
                inband, buffers = await transfer.pull_object(
                    oid_hex, [conn], 60.0,
                    chunk_bytes=self.PULL_CHUNK_BYTES,
                )
                seg_lens = [len(inband)] + [len(b) for b in buffers]
                payload = bytes(inband) + b"".join(
                    bytes(b) for b in buffers
                )
                blob = _remote.pack_object(seg_lens, payload)
                await asyncio.to_thread(tier.put_object, oid_hex, blob)
                EVACUATED.inc(1, tags={"outcome": "remote_tier"})
            except (
                rpc.ConnectionLost,
                rpc.RpcError,
                ObjectLostError,
                _remote.RemoteTierError,
            ) as e:
                EVACUATED.inc(1, tags={"outcome": "failed"})
                logger.warning(
                    "drain evacuation of %s to remote tier failed: %s",
                    oid_hex[:12], e,
                )

    async def _remote_tier_fetch(
        self, oid_hex: str
    ) -> tuple[str, Any] | None:
        """Last rung of the resolution ladder: a drain-evacuated copy in
        the remote spill tier. Returns ("hit", value) or None — the
        object's value may itself be None, so a sentinel tuple
        disambiguates."""
        from ray_tpu.checkpoint import remote as _remote

        try:
            tier = _remote.get_tier()
            if tier is None:
                return None
            blob = await asyncio.to_thread(tier.get_object, oid_hex)
        except _remote.RemoteTierError as e:
            logger.debug("remote-tier fetch of %s failed: %s",
                         oid_hex[:12], e)
            return None
        if blob is None:
            return None
        seg_lens, payload = _remote.unpack_object(blob)
        mv, segs, pos = memoryview(payload), [], 0
        for n in seg_lens:
            segs.append(bytes(mv[pos:pos + n]))
            pos += n
        inband, buffers = segs[0], segs[1:]
        try:
            self.store.put(
                ObjectID.from_hex(oid_hex), Serialized(inband, buffers)
            )
            self.memory[oid_hex] = ("in_store",)
        # tpulint: allow(broad-except reason=local re-cache is best-effort; the tier copy stays authoritative and the value is returned regardless)
        except Exception:
            pass
        logger.info("restored object %s… from the remote tier",
                    oid_hex[:12])
        return ("hit", deserialize(inband, buffers))

    # -------------------------------------------------------------- put
    async def put(self, value: Any):
        from ray_tpu.api import ObjectRef

        self._put_index += 1
        oid = ObjectID.for_put(self._root_task, self._put_index)
        data = serialize(value)
        if data.total_bytes() <= INLINE_MAX_BYTES and self.mode != "client":
            m = data.materialize_buffers()
            self._store_result(oid.hex(), ("value", m.inband, m.buffers))
        elif self.mode == "client":
            # Remote driver: our private store is unreachable from the
            # cluster — upload the bytes (EVERY put, inline-sized too:
            # the client may sit behind NAT) to an anchor node whose
            # store serves every worker's pull (reference: Ray Client
            # server-side put). The ANCHOR becomes the ref's owner
            # address so workers resolve it against the cluster node,
            # never dialing back into the client.
            anchor_addr, anchor = await self._anchor_node()
            m = data.materialize_buffers()
            if data.total_bytes() <= self.PULL_CHUNK_BYTES:
                await anchor.call(
                    "put_object",
                    oid_hex=oid.hex(),
                    inband=m.inband,
                    buffers=m.buffers,
                )
            else:
                await self._upload_chunked(anchor, oid.hex(), m)
            self._store_result(oid.hex(), ("in_store", anchor_addr))
            return ObjectRef(oid.hex(), anchor_addr)
        else:
            self.store.put(oid, data)
            self._store_result(oid.hex(), ("in_store",))
        return ObjectRef(oid.hex(), self.addr)

    async def _upload_chunked(self, anchor, oid_hex: str, m):
        """Stream a large client put to the anchor node in 5 MiB windows
        (mirrors the pull protocol's chunking; one oversized frame would
        hit the rpc frame cap)."""
        segs = [m.inband, *m.buffers]
        reply = await anchor.call(
            "put_object_begin",
            oid_hex=oid_hex,
            seg_lens=[len(s) for s in segs],
        )
        if not reply.get("ok"):
            raise rpc.RpcError(reply.get("error", "put_object_begin failed"))
        token = reply["token"]
        from ray_tpu.runtime.object_store import segment_window

        class _Segs:  # duck-typed view for segment_window
            inband = segs[0]
            buffers = segs[1:]

        total = sum(len(s) for s in segs)
        offset = 0
        while offset < total:
            chunk = segment_window(_Segs, offset, self.PULL_CHUNK_BYTES)
            ack = await anchor.call(
                "put_object_chunk", token=token, offset=offset, data=chunk
            )
            if not ack.get("ok"):
                raise rpc.RpcError("put_object_chunk failed")
            offset += len(chunk)
        done = await anchor.call("put_object_commit", token=token)
        if not done.get("ok"):
            raise rpc.RpcError("put_object_commit failed")

    async def _anchor_node(self) -> tuple[str, rpc.Connection]:
        if self._anchor is not None:
            addr, conn = self._anchor
            if not conn._closed:
                return self._anchor
        pick = await self.head.call("pick_node", resources={})
        if not pick.get("ok"):
            raise rpc.RpcError("client mode: no cluster node to anchor on")
        conn = await self._connect(pick["addr"])
        self._anchor = (pick["addr"], conn)
        return self._anchor

    # -------------------------------------------------------------- get
    async def _get_one(
        self,
        oid_hex: str,
        owner_addr: str,
        timeout: float | None,
        _recon: int = 2,
    ) -> Any:
        """Resolve one ref. ``timeout`` is a SINGLE deadline across all
        stages (owner lookup, chunked pull, reconstruction). Values that
        are already local resolve even with timeout=0 (the deadline only
        gates stages that must do remote work)."""
        if oid_hex in self.memory:
            # _maybe_pull_record tries the synchronous read before its
            # own deadline is ever consulted.
            return await self._maybe_pull_record(oid_hex, timeout)
        oid = ObjectID.from_hex(oid_hex)
        view = self.store.get(oid)
        if view is not None:
            return deserialize(view.inband, view.buffers)
        remaining = self._deadline_of(timeout, f"object {oid_hex[:12]}…")
        if owner_addr == self.addr or oid_hex in self._waiters or (
            owner_addr is None
        ):
            await self._wait_local(oid_hex, remaining())
            return await self._maybe_pull_record(oid_hex, remaining())
        # Ask the owner (reference: OwnershipBasedObjectDirectory).
        conn = await self._connect(owner_addr)
        try:
            reply = await asyncio.wait_for(
                conn.call("get_object", oid_hex=oid_hex), remaining()
            )
        except asyncio.TimeoutError:
            raise GetTimeoutError(
                f"timed out asking the owner for {oid_hex[:12]}…"
            )
        if reply["kind"] == "value":
            return deserialize(reply["inband"], reply["buffers"])
        if reply["kind"] == "tensor":
            return await self._fetch_tensor(
                oid_hex, reply["meta"], remaining()
            )
        if reply["kind"] == "in_store":
            view = self.store.get(oid)
            if view is not None:
                return deserialize(view.inband, view.buffers)
            # The object lives in a node store elsewhere: pull it in
            # pipelined chunks, striped across EVERY node known to hold
            # a copy (reference: pull_manager.h:50 windowed chunk
            # requests; locations from the owner's directory like
            # ownership_object_directory.h), then cache it locally. The
            # owner connection rides along as last-resort source, so
            # stale/evicted holder sets can't lose a servable object.
            from ray_tpu.runtime import transfer

            srcs, addr_of = await transfer.connect_sources(
                reply.get("holders"),
                reply.get("holder"),
                self.node_addr,
                lambda a: self._connect(a, retries=1),
                fallback=conn,
            )
            try:
                return await self._pull_remote(
                    oid, srcs, conn, remaining(), addr_of
                )
            except GetTimeoutError:
                raise
            except (rpc.ConnectionLost, rpc.RpcError, ObjectLostError) as e:
                # Holder gone or copy evicted: ask the OWNER to
                # reconstruct via lineage, then re-resolve.
                if _recon > 0:
                    try:
                        fixed = await asyncio.wait_for(
                            conn.call(
                                "reconstruct_object", oid_hex=oid_hex
                            ),
                            remaining(),
                        )
                    except asyncio.TimeoutError:
                        raise GetTimeoutError(
                            f"timed out reconstructing {oid_hex[:12]}…"
                        ) from e
                    if fixed.get("ok"):
                        return await self._get_one(
                            oid_hex, owner_addr, remaining(), _recon - 1
                        )
                hit = await self._remote_tier_fetch(oid_hex)
                if hit is not None:
                    return hit[1]
                raise ObjectLostError(
                    f"object {oid_hex[:12]}… lost and not "
                    f"reconstructable by its owner: {e}"
                ) from e
        if reply["kind"] == "error":
            raise deserialize(reply["inband"])
        raise AssertionError(reply["kind"])

    PULL_CHUNK_BYTES = 5 * 1024 * 1024  # object_manager_default_chunk_size

    async def _pull_remote(
        self, oid, srcs: list, owner_conn, timeout, addr_of: dict | None = None
    ):
        """Pipelined multi-source pull of a store-resident object
        (reference: pull_manager.h:50). ``timeout`` bounds the WHOLE
        pull, matching get()'s single-deadline semantics. On success the
        copy is cached in this node's store and the owner is told about
        the new location, so later pullers fan in from here too; holders
        that proved dead are reported for pruning."""
        from ray_tpu.runtime import transfer

        oid_hex = oid.hex()
        failed: set = set()
        try:
            inband, buffers = await transfer.pull_object(
                oid_hex,
                srcs,
                timeout,
                chunk_bytes=self.PULL_CHUNK_BYTES,
                failed=failed,
            )
        finally:
            if failed and addr_of:
                bad = [addr_of[c] for c in failed if c in addr_of]
                if bad:
                    await self._prune_locations(oid_hex, bad, owner_conn)
        # Cache locally so later readers on this node hit the store.
        try:
            self.store.put(oid, Serialized(inband, list(buffers)))
        # tpulint: allow(broad-except reason=local cache put is best-effort; the value is already in hand and returned to the caller regardless)
        except Exception:
            pass
        else:
            if self.node_addr:
                if owner_conn is None:
                    # We ARE the owner (self-owned object whose bytes
                    # lived on another node): record the new copy
                    # directly.
                    self._locations.setdefault(oid_hex, set()).add(
                        self.node_addr
                    )
                else:
                    try:
                        await owner_conn.call(
                            "object_location_add",
                            oid_hex=oid_hex,
                            addr=self.node_addr,
                        )
                    except (rpc.ConnectionLost, rpc.RpcError):
                        pass  # owner gone; registry dies with it
        return deserialize(inband, buffers)


    async def broadcast_object(
        self, ref, timeout: float | None = None
    ) -> dict:
        """Relay-broadcast a store-resident object into every node's
        store in doubling waves (reference: push_manager.h:28 pipelined
        pushes — a put-then-fan-out there floods from the single owner;
        here each wave's finishers register as locations, so wave k
        pulls stripe across 2^k sources: a broadcast tree through node
        stores)."""
        oid_hex = ref.hex
        owner_addr = ref.owner_addr or self.addr
        table = await self.head.call("node_table")
        addrs = [n["addr"] for n in table.values() if n.get("addr")]
        conn = await self._connect(owner_addr)
        reply = await conn.call("get_object", oid_hex=oid_hex)
        if reply["kind"] == "value":
            # Inline object: nothing store-resident to relay.
            return {"nodes": 0, "bytes": 0, "inline": True}
        if reply["kind"] != "in_store":
            raise ValueError(
                f"broadcast needs a store-resident object, got "
                f"{reply['kind']!r}"
            )
        holders = set(reply.get("holders") or [])
        if reply.get("holder"):
            holders.add(reply["holder"])
        pending = [a for a in addrs if a not in holders]
        sources = max(1, len(holders))
        # Wave width doubles with the source set but is capped: more
        # concurrent pulls than links just thrash buffers (measured on
        # loopback; real clusters bound this by per-node NIC anyway).
        max_wave = 4
        transferred = cached = waves = 0
        failed: list = []
        while pending:
            width = min(sources, max_wave)
            wave, pending = pending[:width], pending[width:]
            waves += 1

            async def prefetch(addr):
                c = await self._connect(addr, retries=1)
                return await c.call(
                    "prefetch_object",
                    oid_hex=oid_hex,
                    owner_addr=owner_addr,
                    timeout=timeout or 120.0,
                )

            results = await asyncio.gather(
                *(prefetch(a) for a in wave), return_exceptions=True
            )
            for addr, r in zip(wave, results):
                # A dead node (e.g. not yet swept from the node table)
                # is skipped, not fatal: the live nodes still get their
                # copy and the caller learns who failed.
                if isinstance(r, BaseException) or not r.get("ok"):
                    failed.append((addr, repr(r)))
                elif r.get("cached"):
                    cached += 1
                    sources += 1
                else:
                    transferred += 1
                    sources += 1
        if transferred + cached == 0 and failed:
            raise ObjectLostError(
                f"broadcast of {oid_hex[:12]}… reached no node: {failed}"
            )
        return {
            "nodes": transferred,
            "cached": cached,
            "failed": failed,
            # Relay-tree depth: doubling waves mean ~log2(n) + cap
            # spill, NOT n sequential pushes — floored in perf CI.
            "waves": waves,
            "inline": False,
        }

    async def get(self, refs: Sequence, timeout: float | None = None) -> list:
        return list(
            await asyncio.gather(
                *(self._get_one(r.hex, r.owner_addr, timeout) for r in refs)
            )
        )

    async def wait(
        self,
        refs: Sequence,
        num_returns: int,
        timeout: float | None,
        fetch_local: bool = True,
    ):
        """Split refs into (ready, not_ready) — reference: wait_manager.h."""

        async def ready(r):
            await self._get_one(r.hex, r.owner_addr, None)
            return r

        pending = {
            asyncio.ensure_future(ready(r)): r for r in refs
        }
        done_refs = []
        try:
            while pending and len(done_refs) < num_returns:
                done, _ = await asyncio.wait(
                    pending,
                    timeout=timeout,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if not done:
                    break  # timeout
                for fut in done:
                    r = pending.pop(fut)
                    # Objects that errored still count as ready.
                    done_refs.append(r)
        finally:
            for fut in pending:
                fut.cancel()
        not_ready = [r for r in refs if r not in done_refs]
        return done_refs, not_ready

    # ----------------------------------------------------- task submit
    async def submit_task(
        self,
        fn: Any,
        args: Sequence,
        kwargs: dict,
        num_returns: int = 1,
        resources: dict | None = None,
        max_retries: int = DEFAULT_RETRIES,
        actor: "ActorSubmitTarget | None" = None,
        placement: tuple | None = None,  # (node_addr, pg_id, bundle_index)
        runtime_env: dict | None = None,
        tensor_transport: Any = None,
        scheduling: dict | None = None,
        trace_ctx: dict | None = None,
    ) -> list:
        """Submit; returns ObjectRefs immediately, result delivery is
        async (the reply fulfils the local futures)."""
        from ray_tpu.api import ObjectRef

        task_id = TaskID.random()
        streaming = num_returns == "streaming"
        if streaming:
            num_returns = 0
            self._generators[task_id.hex()] = asyncio.Queue()
        oids = [
            ObjectID.for_return(task_id, i).hex() for i in range(num_returns)
        ]
        for oid_hex in oids:
            self._waiters.setdefault(oid_hex, [])

        # Actor calls carry the method *name*; normal tasks export the
        # function to the cluster KV and carry its id. "cfn:<name>"
        # targets a function DEFINED in a foreign worker (C++
        # RAYTPU_REMOTE registration): nothing to export — the name is
        # resolved inside the executing worker's own registry, args and
        # results cross as msgpack (reference: cross_language.py
        # cpp_function + ray_remote.h).
        xlang_target = isinstance(fn, str) and fn.startswith("cfn:")
        if xlang_target:
            if num_returns != 1:
                # The foreign worker replies with exactly one msgpack
                # result; extra return refs would never resolve.
                raise ValueError(
                    "cross-language tasks return exactly one value "
                    f"(got num_returns={num_returns!r})"
                )
            fn_id = fn
        else:
            fn_id = fn if actor is not None else await self.export_function(fn)
        spec = {
            "task_id": task_id.hex(),
            "fn_id": fn_id,
            "name": (
                fn if isinstance(fn, str) else getattr(fn, "__name__", "")
            ),
            "args": (
                self._encode_args_mp(args, kwargs)
                if xlang_target
                else self._encode_args(args, kwargs)
            ),
            "num_returns": num_returns,
            "owner_addr": self.addr,
        }
        if xlang_target:
            spec["xlang"] = True
        if streaming:
            spec["streaming"] = True
            self._gen_attempt[task_id.hex()] = 0
        if tensor_transport is not None:
            spec["tensor_transport"] = tensor_transport
        if trace_ctx is None:
            from ray_tpu.util import tracing

            trace_ctx = tracing.make_trace_ctx(spec["name"] or spec["fn_id"])
        if trace_ctx is not None:
            spec["trace"] = trace_ctx
        self.record_task_event(
            spec, "SUBMITTED", kind="actor_task" if actor else "task"
        )
        if actor is None and not streaming and max_retries > 0:
            # Lineage for reconstruction: enough to resubmit this task if
            # a store-resident return is later lost (actor methods are
            # not idempotent; streams replay only from the start — both
            # excluded, matching this runtime's retry semantics).
            entry_bytes = _spec_nbytes(spec)
            budget = config.get("MAX_LINEAGE_BYTES")
            if entry_bytes <= budget:
                # An entry larger than the whole budget is skipped
                # outright — recording it would evict every OTHER
                # entry first (destroying their reconstructability)
                # and then itself; its returns are simply
                # unreconstructable, like reference tasks past
                # RAY_max_lineage_bytes.
                self._lineage[task_id.hex()] = {
                    "spec": spec,
                    "oids": oids,
                    "bytes": entry_bytes,
                    "resources": resources,
                    "placement": placement,
                    "runtime_env": runtime_env,
                    "scheduling": scheduling,
                    "attempts_left": max_retries,
                }
                for oid_hex in oids:
                    self._oid_to_task[oid_hex] = task_id.hex()
                self._lineage_bytes += entry_bytes
                while self._lineage and (
                    len(self._lineage) > self._lineage_cap
                    or self._lineage_bytes > budget
                ):
                    old_tid, old = self._lineage.popitem(last=False)
                    self._lineage_bytes -= old.get("bytes", 0)
                    for oid_hex in old["oids"]:
                        self._oid_to_task.pop(oid_hex, None)
        asyncio.ensure_future(
            self._drive_task(
                spec, oids, resources, max_retries, actor, placement,
                runtime_env, scheduling,
            )
        )
        if streaming:
            return task_id.hex()
        return [ObjectRef(o, self.addr) for o in oids]

    async def _drive_task(
        self, spec, oids, resources, retries, actor, placement,
        runtime_env=None, scheduling=None,
    ):
        try:
            if actor is not None:
                errored = await self._drive_actor_task(spec, oids, actor)
            else:
                errored = await self._drive_normal_task(
                    spec, oids, resources, retries, placement, runtime_env,
                    scheduling,
                )
            self.record_task_event(
                spec, "FAILED" if errored else "FINISHED"
            )
        # tpulint: allow(broad-except reason=not swallowed - the error is recorded as the task FAILED event and stored as the result the owner reads)
        except Exception as e:
            self.record_task_event(
                spec,
                "CANCELLED" if isinstance(e, TaskCancelledError) else "FAILED",
                error=repr(e),
            )
            for oid_hex in oids:
                self._store_result(oid_hex, ("error", e))
            if spec.get("streaming"):
                q = self._generators.get(spec["task_id"])
                if q is not None:
                    q.put_nowait(("error", e))

    # ------------------------------------------------- lineage recovery
    async def _reconstruct(
        self, oid_hex: str, timeout: float | None = None
    ) -> bool:
        """Re-execute the task that created a lost object (reference:
        lineage reconstruction, object_recovery_manager.h:41). Returns
        True when a fresh result record is in place. Concurrent callers
        for the same task share ONE resubmission, which runs as a
        background task — a caller timing out (or being cancelled)
        neither cancels the re-execution nor strands other waiters."""
        task_id = self._oid_to_task.get(oid_hex)
        entry = self._lineage.get(task_id) if task_id else None
        if entry is None:
            return False
        inflight = self._reconstructing.get(task_id)
        if inflight is None:
            if entry["attempts_left"] <= 0:
                return False
            entry["attempts_left"] -= 1
            inflight = asyncio.ensure_future(
                self._do_reconstruct(task_id, entry)
            )
            self._reconstructing[task_id] = inflight
            inflight.add_done_callback(
                lambda _t: self._reconstructing.pop(task_id, None)
            )
        try:
            return await asyncio.wait_for(asyncio.shield(inflight), timeout)
        except asyncio.TimeoutError:
            raise GetTimeoutError(
                f"timed out while reconstructing {oid_hex[:12]}…"
            )

    async def _do_reconstruct(self, task_id: str, entry: dict) -> bool:
        self.record_task_event(entry["spec"], "RECONSTRUCTING")
        # Drop stale store-location records so fresh results land and
        # blocked readers wake on the new value. Inline ("value")
        # records are still good — keep them.
        for o in entry["oids"]:
            rec = self.memory.get(o)
            if rec is not None and rec[0] == "in_store":
                self.memory.pop(o, None)
                self.store.release(ObjectID.from_hex(o))
        try:
            errored = await self._drive_normal_task(
                entry["spec"],
                entry["oids"],
                entry["resources"],
                1,
                entry["placement"],
                entry["runtime_env"],
                entry.get("scheduling"),
            )
        # tpulint: allow(broad-except reason=not swallowed - the failure is stored as an error record so blocked readers fail with the cause)
        except Exception as e:
            # Leave an error record so readers that blocked on the
            # cleared oids fail with the cause instead of waiting
            # forever.
            for o in entry["oids"]:
                if o not in self.memory:
                    self._store_result(
                        o,
                        (
                            "error",
                            ObjectLostError(
                                f"object {o[:12]}… reconstruction "
                                f"failed: {e}"
                            ),
                        ),
                    )
            return False
        return not errored

    async def _on_reconstruct_object(self, conn, oid_hex: str):
        """Borrower-requested reconstruction: a non-owner whose pull
        failed asks the owner to re-execute the creating task."""
        return {"ok": await self._reconstruct(oid_hex)}

    # ------------------------------------------------------ cancellation
    async def cancel_task(self, oid_hex: str) -> bool:
        """Cancel the normal task producing ``oid_hex`` (reference:
        CoreWorker::CancelTask; python cancel semantics worker.py).
        Queued tasks fail fast with TaskCancelledError; a running task's
        worker is force-killed (execution threads cannot be safely
        interrupted — same as the reference's force path). Returns False
        when the task already finished."""
        from ray_tpu._private.ids import TaskID

        task_id = oid_hex[: TaskID.LENGTH * 2]  # return ids embed it
        state = self._cancel_state.get(task_id)
        if state is None:
            return False
        state["cancelled"] = True
        lease = state.get("lease")
        if lease is not None:
            node_conn = lease.get("node_conn") or self.node
            if node_conn is not None:
                try:
                    await node_conn.call(
                        "kill_worker", worker_id=lease["worker_id"]
                    )
                except (rpc.ConnectionLost, rpc.RpcError):
                    pass
        else:
            # Still queued (possibly blocked on a lease wait that only
            # resolves when capacity frees): deliver the cancellation to
            # readers NOW — the drive loop notices and unwinds whenever
            # its lease finally arrives.
            err = TaskCancelledError(f"task {task_id[:12]}… was cancelled")
            for o in state.get("oids") or []:
                if o not in self.memory:
                    self._store_result(o, ("error", err))
        return True

    async def _on_cancel_task(self, conn, oid_hex: str):
        """Borrower-side cancel routed to the owner."""
        return {"ok": await self.cancel_task(oid_hex)}

    # ------------------------------------------------- tensor transport
    async def _fetch_tensor(self, oid_hex: str, meta: dict, timeout=None):
        """Resolve a tensor-transport ref: payload moves point-to-point
        from the producing actor (reference: gpu_object_manager
        transports — collective_tensor_transport.py / nixl). When this
        process shares the producer's collective group, the transfer
        rides the group's send/recv data plane; otherwise a chunked rpc
        fetch from the producer (never via the owner or object store).
        ``timeout`` is one deadline across every stage; fetched values
        are cached so repeat gets do not re-transfer."""
        if oid_hex in self.tensor_store:
            return self.tensor_store[oid_hex]  # we are the producer
        if oid_hex in self._tensor_cache:
            self._tensor_cache.move_to_end(oid_hex)
            return self._tensor_cache[oid_hex]
        remaining = self._deadline_of(timeout, f"tensor {oid_hex[:12]}…")
        value = await self._fetch_tensor_payload(oid_hex, meta, remaining)
        self._tensor_cache[oid_hex] = value
        while len(self._tensor_cache) > self._tensor_cache_cap:
            self._tensor_cache.popitem(last=False)
        return value

    async def _fetch_tensor_payload(self, oid_hex, meta, remaining):
        group_name = meta.get("group")
        if group_name is not None and meta.get("src_rank") is not None:
            from ray_tpu import collective as col

            if col.is_group_initialized(group_name):
                g = col.get_group(group_name)
                if getattr(g, "rank", None) is not None and (
                    g.rank != meta["src_rank"]
                ):
                    # Ask the producer to post a send tagged with this
                    # ref; the payload lands in our group mailbox even
                    # before recv is posted, so send-then-recv is safe.
                    seq = int(oid_hex[:12], 16)
                    try:
                        conn = await self._connect(meta["src_addr"])
                        ack = await asyncio.wait_for(
                            conn.call(
                                "tensor_send",
                                oid_hex=oid_hex,
                                dst_rank=g.rank,
                                group_name=group_name,
                                seq=seq,
                            ),
                            remaining(),
                        )
                        if ack.get("ok"):
                            return await asyncio.wait_for(
                                g.recv(meta["src_rank"], seq=seq),
                                remaining(),
                            )
                    except asyncio.TimeoutError:
                        raise GetTimeoutError(
                            f"timed out fetching tensor {oid_hex[:12]}… "
                            f"over group {group_name!r}"
                        )
                    except (rpc.ConnectionLost, rpc.RpcError):
                        pass  # backend lacks send/recv etc. — rpc fetch
        conn = await self._connect(meta["src_addr"])
        try:
            reply = await asyncio.wait_for(
                conn.call("fetch_tensor", oid_hex=oid_hex), remaining()
            )
            if not reply.get("ok"):
                raise ObjectLostError(
                    f"tensor {oid_hex[:12]}… is gone from its producer "
                    f"(actor died or tensor freed)"
                )
            if not reply.get("chunked"):
                return deserialize(reply["inband"], reply["buffers"])
            # Large tensor: pull the serialized stream in store-sized
            # chunks (mirrors _pull_remote's 5 MiB protocol).
            token, total = reply["token"], reply["total"]
            seg_lens = reply["seg_lens"]
            parts = []
            offset = 0
            while offset < total:
                chunk = await asyncio.wait_for(
                    conn.call(
                        "fetch_tensor_chunk",
                        token=token,
                        offset=offset,
                        size=self.PULL_CHUNK_BYTES,
                    ),
                    remaining(),
                )
                if not chunk.get("ok"):
                    raise ObjectLostError(
                        f"tensor {oid_hex[:12]}… fetch failed mid-stream"
                    )
                parts.append(chunk["data"])
                offset += len(chunk["data"])
        except asyncio.TimeoutError:
            raise GetTimeoutError(
                f"timed out fetching tensor {oid_hex[:12]}…"
            )
        blob = b"".join(parts)
        segs = []
        pos = 0
        for n in seg_lens:
            segs.append(blob[pos : pos + n])
            pos += n
        return deserialize(segs[0], segs[1:])

    _TENSOR_EXPORT_CAP = 8

    async def _on_fetch_tensor(self, conn, oid_hex: str):
        if oid_hex not in self.tensor_store:
            return {"ok": False}
        value = self.tensor_store[oid_hex]
        data = serialize(value).materialize_buffers()
        total = data.total_bytes()
        if total <= self.PULL_CHUNK_BYTES:
            return {
                "ok": True,
                "inband": data.inband,
                "buffers": data.buffers,
            }
        # Oversized for one rpc frame: stash the serialized segments in
        # an export buffer and let the consumer pull windows.
        token = f"{oid_hex}:{id(data)}"
        self._tensor_exports[token] = (
            [data.inband, *data.buffers],
            total,
            time.time(),
        )
        # Evict only STALE exports (no chunk pulled for 60s): an active
        # stream must never lose its buffer mid-pull, so the cap is a
        # soft target under concurrent fetch bursts.
        if len(self._tensor_exports) > self._TENSOR_EXPORT_CAP:
            now = time.time()
            for key in list(self._tensor_exports):
                if key != token and now - self._tensor_exports[key][2] > 60:
                    del self._tensor_exports[key]
        return {
            "ok": True,
            "chunked": True,
            "token": token,
            "total": total,
            "seg_lens": [len(data.inband)] + [len(b) for b in data.buffers],
        }

    async def _on_fetch_tensor_chunk(
        self, conn, token: str, offset: int, size: int
    ):
        entry = self._tensor_exports.get(token)
        if entry is None:
            return {"ok": False}
        segs, total, _ts = entry
        # Refresh the staleness clock: an active stream is never evicted.
        self._tensor_exports[token] = (segs, total, time.time())
        out = bytearray()
        pos = 0
        for seg in segs:
            seg_len = len(seg)
            if offset < pos + seg_len and len(out) < size:
                start = max(0, offset - pos)
                take = min(seg_len - start, size - len(out))
                out += memoryview(seg)[start : start + take]
            pos += seg_len
            if len(out) >= size:
                break
        if offset + len(out) >= total:  # stream complete: free buffer
            self._tensor_exports.pop(token, None)
        return {"ok": True, "data": bytes(out)}

    async def _on_tensor_send(
        self, conn, oid_hex: str, dst_rank: int, group_name: str, seq: int
    ):
        """Producer side of a collective-path transfer: post a send of
        the stored tensor toward the requesting rank."""
        if oid_hex not in self.tensor_store:
            return {"ok": False}
        value = self.tensor_store[oid_hex]
        if not (hasattr(value, "shape") and hasattr(value, "dtype")):
            # Group send carries single arrays; pytrees take the rpc
            # fetch path instead.
            return {"ok": False, "error": "value is not a single array"}
        from ray_tpu import collective as col

        if not col.is_group_initialized(group_name):
            return {"ok": False, "error": f"no group {group_name!r} here"}
        group = col.get_group(group_name)
        send = getattr(group, "send", None)
        if send is None:
            return {"ok": False, "error": "group backend has no send"}
        await send(value, dst_rank, seq=seq)
        return {"ok": True}

    async def _on_drop_tensor(self, conn, oid_hex: str):
        self.tensor_store.pop(oid_hex, None)
        return {"ok": True}

    async def free_tensor(self, oid_hex: str) -> bool:
        """Owner-side tensor freeing (reference: GPU objects are freed
        eagerly once out of scope; here freeing is explicit via
        ray_tpu.experimental.free_tensors): drop the producer's pinned
        payload and poison the record."""
        rec = self.memory.get(oid_hex)
        if rec is None or rec[0] != "tensor":
            return False
        meta = rec[1]
        try:
            src = await self._connect(meta["src_addr"])
            await src.call("drop_tensor", oid_hex=oid_hex)
        except (rpc.ConnectionLost, rpc.RpcError):
            # Producer unreachable: leave the record intact so the
            # caller can retry (poisoning now would leak the pinned
            # payload forever if the producer is only briefly away).
            return False
        self._store_result(
            oid_hex,
            ("error", ObjectLostError(f"tensor {oid_hex[:12]}… was freed")),
        )
        return True

    async def _on_free_tensor(self, conn, oid_hex: str):
        return {"ok": await self.free_tensor(oid_hex)}

    # -------------------------------------------------------- task events
    def record_task_event(self, spec: dict, state: str, **extra):
        ev = {
            "task_id": spec.get("task_id", ""),
            "name": spec.get("name", spec.get("fn_id", ""))[:80],
            "state": state,
            "ts": time.time(),
            "worker": self.addr,
        }
        ev.update(extra)
        self._task_events.append(ev)
        if len(self._task_events) > 10000:  # drop oldest under pressure
            del self._task_events[:5000]

    async def _flush_events(self):
        if not self._task_events or self.head is None:
            return
        batch, self._task_events = self._task_events, []
        try:
            await self.head.call("add_task_events", events=batch)
        # tpulint: allow(broad-except reason=1 Hz flush loop against a possibly-degraded head; logging every miss would spam - events re-flush next tick)
        except Exception:
            pass

    async def flush_observability(self):
        """Eagerly drain buffered task events and push a metrics
        snapshot — the 1 Hz loop's work, on demand. Called at moments
        the process may be about to die (a train attempt ending), so
        the last second of spans/metrics isn't lost with the worker."""
        from ray_tpu.util import metrics as _metrics

        await self._flush_events()
        snap = _metrics.snapshot()
        if snap:
            try:
                await self.head.call(
                    "report_metrics", worker=self.addr, metrics=snap
                )
            # tpulint: allow(broad-except reason=eager pre-death flush; the head may already be unreachable and there is nobody left to tell)
            except Exception:
                pass

    async def _flush_events_loop(self):
        while True:
            await asyncio.sleep(1.0)
            await self.flush_observability()

    async def _drive_normal_task(
        self, spec, oids, resources, retries, placement=None,
        runtime_env=None, scheduling=None,
    ):
        last_err: Exception | None = None
        tid = spec["task_id"]
        state = self._cancel_state.setdefault(
            tid, {"cancelled": False, "lease": None, "oids": oids}
        )
        try:
            for attempt in range(retries + 1):
                lease = None
                try:
                    if state["cancelled"]:
                        raise TaskCancelledError(
                            f"task {tid[:12]}… was cancelled"
                        )
                    if spec.get("streaming"):
                        # Stamp the attempt so late item reports from a
                        # dead earlier attempt can't interleave.
                        spec = {**spec, "attempt": attempt}
                        self._gen_attempt[spec["task_id"]] = attempt
                    # Resolve self-owned deps BEFORE leasing (reference:
                    # LocalDependencyResolver dependency_resolver.h:36 —
                    # no worker is held while upstream tasks run, and
                    # arg locations are known for the locality hint).
                    await self._wait_own_deps(spec)
                    lease = await self._lease(
                        resources, placement, runtime_env, scheduling,
                        locality=self._locality_hint(spec),
                    )
                    if state["cancelled"]:  # cancelled while queued
                        raise TaskCancelledError(
                            f"task {tid[:12]}… was cancelled"
                        )
                    state["lease"] = lease
                    try:
                        conn = await self._connect(lease["addr"])
                    except (rpc.ConnectionLost, OSError) as e:
                        # Dial failure = the leased WORKER is unreachable
                        # (dead). Returning the lease would re-idle the
                        # corpse and hand it to the next caller — drop it
                        # (the node's reap loop reconciles) and retry on
                        # a fresh lease. sent=False here means "safe to
                        # resend", not "the worker is alive".
                        last_err = e
                        lease = None
                        continue
                    reply = await conn.call("push_task", spec=spec)
                    return self._apply_reply(reply, oids, spec["task_id"])
                except (rpc.ConnectionLost, rpc.RpcError, OSError) as e:
                    # OSError: connect() translates ConnectionError but a
                    # dead peer can still surface other socket errors —
                    # they mean the same thing here (worker unreachable).
                    last_err = e
                    if state["cancelled"]:
                        # The kill we issued took the worker down
                        # mid-push: this is cancellation, not failure —
                        # never retry.
                        lease = None
                        raise TaskCancelledError(
                            f"task {tid[:12]}… was cancelled while running"
                        ) from e
                    if spec.get("streaming") and self._gen_delivered.get(
                        spec["task_id"], 0
                    ):
                        # Items were already delivered: a retry would
                        # replay them. Fail instead (reference:
                        # generators restart only via lineage
                        # reconstruction, not mid-stream).
                        if getattr(e, "sent", True):
                            lease = None
                        break
                    if not getattr(e, "sent", True):
                        # The request never reached the worker (closed
                        # conn caught locally, chaos drop): the lease is
                        # intact — the finally clause returns it.
                        continue
                    lease = None  # worker may be gone; don't return it
                    continue
                finally:
                    state["lease"] = None
                    if lease is not None:
                        await self._return_lease(lease)
            raise WorkerDiedError(
                f"task failed after {retries + 1} attempts: {last_err}"
            )
        finally:
            self._cancel_state.pop(tid, None)

    async def _drive_actor_task(self, spec, oids, actor):
        # Prefer the freshest known address: the actor may have been
        # restarted on a different worker since this handle was created.
        failure: Exception | None = None
        dialed_dead = False
        addr = actor.addr
        for _ in range(5):
            addr = self._actor_addrs.get(actor.actor_id, actor.addr)
            try:
                conn = await self._connect(addr)
            except (rpc.ConnectionLost, OSError) as e:
                # Endpoint unreachable (worker process gone): the actor
                # is dead — fall through to the head-driven restart.
                # The request provably never hit the wire, so it is
                # safe to RETRY against the restarted address below.
                failure = e
                dialed_dead = True
                break
            try:
                reply = await conn.call(
                    "actor_call", spec=spec, actor_id=actor.actor_id
                )
                return self._apply_reply(reply, oids, spec["task_id"])
            except (rpc.ConnectionLost, rpc.RpcError, OSError) as e:
                failure = e
                if not getattr(e, "sent", True):
                    # Never reached the wire (chaos drop / locally-closed
                    # conn): the actor is fine — resend, don't restart.
                    # Only evict the cached conn if it actually closed (a
                    # chaos drop leaves it healthy; evicting would leak
                    # the socket and its recv task).
                    cached = self._conns.get(addr)
                    if cached is not None and cached._closed:
                        self._conns.pop(addr, None)
                    continue
                break
        else:
            raise ActorDiedError(
                f"actor {actor.actor_id[:12]}…: request could not be sent"
            ) from failure

        # The connection died. Report to the head; it restarts the actor
        # if max_restarts allows. A call that was (possibly) DELIVERED
        # still fails (it may have half-executed — actor methods are not
        # idempotent by default); a call that provably never reached the
        # wire retries once against the restarted address.
        try:
            reply = await self.head.call(
                "restart_actor", actor_id=actor.actor_id, failed_addr=addr
            )
        except rpc.RpcError:
            reply = {"ok": False}
        if reply.get("ok"):
            self._actor_addrs[actor.actor_id] = reply["addr"]
            if dialed_dead and not spec.pop("_restart_retried", False):
                spec["_restart_retried"] = True  # one retry, no loops
                return await self._drive_actor_task(spec, oids, actor)
            raise ActorDiedError(
                f"actor {actor.actor_id[:12]}… died mid-call and was "
                f"restarted; this call was lost: {failure}"
            ) from failure
        raise ActorDiedError(
            f"actor {actor.actor_id[:12]}… died: {failure}"
        ) from failure

    def _apply_reply(
        self, reply: dict, oids: list, task_id: str | None = None
    ) -> bool:
        """Returns True when the reply carries a task error."""
        if reply["status"] == "error":
            if "error" in reply:
                err = deserialize(reply["error"])
            else:
                # A foreign (C++) worker cannot pickle a RayTaskError;
                # it sends the text only.
                err = RayTaskError(
                    reply.get("error_text") or "foreign task failed"
                )
            for oid_hex in oids:
                self._store_result(oid_hex, ("error", err))
            if task_id is not None:
                q = self._generators.get(task_id)
                if q is not None:  # streaming task failed mid-iteration
                    q.put_nowait(("error", err))
            return True
        for oid_hex, kind, *rest in reply["results"]:
            if kind == "inline":
                self._store_result(oid_hex, ("value", rest[0], rest[1]))
            elif kind == "tensor":  # payload stays in the producer
                self._store_result(oid_hex, ("tensor", rest[0]))
            elif kind == "xmp":
                # Cross-language result: msgpack from a foreign worker,
                # re-serialized into the owner's normal value path.
                s = serialize(
                    rpc.unpack_frame(rest[0])
                ).materialize_buffers()
                self._store_result(
                    oid_hex, ("value", s.inband, s.buffers)
                )
            else:  # in a node's shared store (rest = [holder_node_addr])
                self._store_result(
                    oid_hex, ("in_store", rest[0] if rest else None)
                )
        return False

    # ------------------------------------------------------------ leases
    def _sched_key(
        self,
        resources: dict | None,
        runtime_env: dict | None = None,
        scheduling: dict | None = None,
    ) -> tuple:
        from ray_tpu.runtime.node import env_hash

        def freeze(value):
            # Canonical recursive form: logically equal strategies with
            # different dict insertion order share one lease pool.
            if isinstance(value, dict):
                return tuple(
                    sorted((k, freeze(v)) for k, v in value.items())
                )
            if isinstance(value, (list, tuple, set)):
                return tuple(sorted(repr(freeze(v)) for v in value))
            return value

        return (
            tuple(sorted((resources or {"CPU": 1.0}).items())),
            env_hash(runtime_env),
            None if scheduling is None else freeze(scheduling),
        )

    async def _wait_own_deps(self, spec: dict) -> None:
        """Wait until every by-ref arg OWNED BY THIS PROCESS reaches a
        terminal state (value, store location, or error). Refs owned by
        other processes resolve at the executing worker as before."""
        for entry in spec.get("args", ()):
            if entry[1] != "ref" or entry[3] != self.addr:
                continue
            await self._wait_local(entry[2], timeout=None)

    def _locality_hint(self, spec: dict) -> str | None:
        """Node holding most of this task's store-resident args, if it is
        not the local node (reference: the locality-aware LeasePolicy,
        lease_policy.h — prefer the raylet already holding the task's
        dependencies so args need no transfer). Only refs THIS process
        owns carry location info; best-effort by design."""
        counts: dict[str, int] = {}
        for entry in spec.get("args", ()):
            if entry[1] != "ref":
                continue
            loc = self.memory.get(entry[2])
            if loc and loc[0] == "in_store":
                # holder None = the LOCAL node's store; it must vote too,
                # or one remote arg outweighs any number of local ones.
                # (put() records are ("in_store",) with no holder slot.)
                holder = (loc[1] if len(loc) > 1 else None) or self.node_addr
                if holder:
                    counts[holder] = counts.get(holder, 0) + 1
        if not counts:
            return None
        best = max(counts, key=lambda a: counts[a])
        return best if best != self.node_addr else None

    async def _lease(
        self,
        resources: dict | None,
        placement: tuple | None = None,
        runtime_env: dict | None = None,
        scheduling: dict | None = None,
        locality: str | None = None,
    ) -> dict:
        if placement is not None:
            # Bundle-backed lease on the bundle's node; never cached.
            node_addr, pg_id, index = placement
            node_conn = (
                self.node
                if node_addr is None
                else await self._connect(node_addr)
            )
            reply = await node_conn.call(
                "lease_worker",
                resources=dict(resources or {"CPU": 1.0}),
                bundle=(pg_id, index),
                runtime_env=runtime_env,
            )
            if not reply.get("ok"):
                raise rpc.RpcError(reply.get("error", "bundle lease failed"))
            reply["sched_key"] = None
            reply["node_conn"] = node_conn
            return reply
        key = self._sched_key(resources, runtime_env, scheduling)
        pool = self._pool(key)
        while pool["free"]:
            lease, _ = pool["free"].pop()
            conn = self._conns.get(lease["addr"])
            if conn is None or not conn._closed:
                return lease
        fut = asyncio.get_running_loop().create_future()
        pool["waiters"].append(fut)
        self._maybe_request_lease(
            key, dict(resources or {"CPU": 1.0}), runtime_env, scheduling,
            locality=locality,
        )
        return await fut

    def _pool(self, key: tuple) -> dict:
        import collections

        return self._lease_pools.setdefault(
            key, {"free": [], "waiters": collections.deque(), "inflight": 0}
        )

    def _maybe_request_lease(
        self,
        key: tuple,
        resources: dict,
        runtime_env: dict | None = None,
        scheduling: dict | None = None,
        locality: str | None = None,
    ):
        """Pipeline lease requests: keep at most min(#waiters, cap)
        requests in flight per scheduling class."""
        pool = self._pool(key)
        if pool["inflight"] >= min(
            len(pool["waiters"]), self._max_inflight_leases
        ):
            return
        pool["inflight"] += 1

        async def request():
            try:
                reply = None
                if (
                    scheduling is None
                    and locality
                    and self.node is not None
                ):
                    # Locality-first: lease from the node already
                    # holding the args. Best-effort — unreachable or
                    # infeasible holder falls through to the normal
                    # local-then-spill path (reference: LeasePolicy
                    # picks the raylet, spillback corrects it).
                    try:
                        lconn = await self._connect(locality)
                        lreply = await lconn.call(
                            "lease_worker",
                            resources=resources,
                            runtime_env=runtime_env,
                        )
                        if lreply.get("ok"):
                            lreply["node_conn"] = lconn
                            reply = lreply
                    except (rpc.RpcError, OSError):
                        pass
                if reply is not None:
                    pass
                elif scheduling is not None:
                    reply = await self._lease_with_strategy(
                        resources, runtime_env, scheduling
                    )
                elif self.node is None:
                    # Client mode: no local node — every lease goes
                    # through the head's placement.
                    reply = await self._spill_lease(
                        resources, runtime_env=runtime_env
                    )
                else:
                    reply = await self.node.call(
                        "lease_worker",
                        resources=resources,
                        runtime_env=runtime_env,
                    )
                    if not reply.get("ok") and (
                        reply.get("infeasible") or reply.get("retry_spill")
                    ):
                        # Local node can never satisfy this (infeasible)
                        # or kept us queued past its age limit
                        # (retry_spill): spill via the head (reference:
                        # lease spillback, retry_at_raylet_address
                        # node_manager.proto:78). If the whole cluster is
                        # infeasible, poll — the autoscaler may add a
                        # node.
                        reply = await self._spill_lease(
                            resources, runtime_env=runtime_env
                        )
                if not reply.get("ok"):
                    raise rpc.RpcError(reply.get("error", "lease failed"))
                reply["sched_key"] = key
                # Locally-granted leases carry their node conn too, so
                # cancellation can reach the right kill_worker endpoint.
                reply.setdefault("node_conn", self.node)
                pool["inflight"] -= 1
                self._offer_lease(key, reply)
            # tpulint: allow(broad-except reason=not swallowed - the lease failure is set on the waiting future and raises at the submit site)
            except Exception as e:
                pool["inflight"] -= 1
                while pool["waiters"]:
                    fut = pool["waiters"].popleft()
                    if not fut.done():
                        fut.set_exception(e)
                        break
            # Top up if demand still outstrips supply.
            if pool["waiters"]:
                self._maybe_request_lease(
                    key, resources, runtime_env, scheduling,
                    locality=locality,
                )

        asyncio.ensure_future(request())

    async def _lease_with_strategy(
        self,
        resources: dict,
        runtime_env: dict | None,
        scheduling: dict,
        actor: bool = False,
    ) -> dict:
        """Lease honoring a scheduling strategy (reference:
        python/ray/util/scheduling_strategies.py — NodeAffinity :43,
        NodeLabel :164; the raylet-side policies
        scheduling/policy/node_affinity_scheduling_policy and
        node_label_scheduling_policy)."""
        node_id = scheduling.get("node_id")
        if node_id is not None:
            info = await self.head.call("get_node", node_id=node_id)
            if not info.get("ok"):
                if scheduling.get("soft"):
                    return await self._spill_lease(
                        resources, actor=actor, runtime_env=runtime_env
                    )
                return {
                    "ok": False,
                    "error": f"node affinity (hard): {info.get('error')}",
                }
            conn = await self._connect(info["addr"])
            while True:
                granted = await conn.call(
                    "lease_worker",
                    resources=resources,
                    actor=actor,
                    runtime_env=runtime_env,
                )
                if granted.get("ok"):
                    granted["node_conn"] = conn
                    return granted
                if granted.get("retry_spill") and not scheduling.get("soft"):
                    # Hard affinity: the node is just busy — keep
                    # queueing on IT rather than spilling elsewhere.
                    await asyncio.sleep(0.2)
                    continue
                if scheduling.get("soft"):
                    return await self._spill_lease(
                        resources, actor=actor, runtime_env=runtime_env
                    )
                return {
                    "ok": False,
                    "error": granted.get(
                        "error", "node affinity lease failed"
                    ),
                }
        # Label strategy: the head filters by hard labels and prefers
        # soft matches.
        return await self._spill_lease(
            resources,
            actor=actor,
            runtime_env=runtime_env,
            pick_kwargs={
                "labels_hard": scheduling.get("labels_hard") or None,
                "labels_soft": scheduling.get("labels_soft") or None,
            },
        )

    async def _spill_lease(
        self,
        resources: dict,
        actor: bool = False,
        runtime_env: dict | None = None,
        pick_kwargs: dict | None = None,
    ) -> dict:
        """Find a feasible node through the head and lease there.

        The timeout clock only runs while the WHOLE cluster is infeasible
        (waiting for the autoscaler); when a feasible node exists but is
        saturated, we keep cycling through its queue indefinitely — a
        busy cluster must not fail queued tasks.
        """
        import uuid

        from ray_tpu._private import config

        loop = asyncio.get_running_loop()
        timeout_s = config.get("SCHED_TIMEOUT_S")
        deadline = loop.time() + timeout_s
        requester = uuid.uuid4().hex  # dedups this wait's demand at the head
        while True:
            reply = await self.head.call(
                "pick_node",
                resources=resources,
                requester=requester,
                **{k: v for k, v in (pick_kwargs or {}).items() if v},
            )
            if reply.get("ok"):
                deadline = loop.time() + timeout_s  # feasible: clock resets
                if reply["addr"] == self.node_addr:
                    conn = self.node
                else:
                    conn = await self._connect(reply["addr"])
                granted = await conn.call(
                    "lease_worker",
                    resources=resources,
                    actor=actor,
                    runtime_env=runtime_env,
                )
                if granted.get("ok"):
                    granted["node_conn"] = conn
                    return granted
                # Chosen node raced away, filled up, or bounced us after
                # its queue-age limit; re-pick.
            if loop.time() >= deadline:
                return {
                    "ok": False,
                    "error": (
                        f"no node can satisfy {resources} (waited "
                        f"{timeout_s}s for scale-up; set "
                        "RAY_TPU_SCHED_TIMEOUT_S to wait longer)"
                    ),
                }
            await asyncio.sleep(0.5)

    def _offer_lease(self, key: tuple, lease: dict):
        import time

        pool = self._pool(key)
        while pool["waiters"]:
            fut = pool["waiters"].popleft()
            if not fut.done():
                fut.set_result(lease)
                return
        if len(pool["free"]) < self._lease_cap:
            pool["free"].append((lease, time.monotonic()))
        else:
            asyncio.ensure_future(self._give_back(lease))

    async def _return_lease(self, lease: dict):
        if lease.get("sched_key") is None:  # bundle lease: return directly
            try:
                await lease["node_conn"].call(
                    "return_lease", lease_id=lease["lease_id"]
                )
            except rpc.RpcError:
                pass
            return
        self._offer_lease(lease["sched_key"], lease)

    async def _give_back(self, lease: dict):
        # Spilled leases carry the conn of the (remote) node that granted
        # them; returning to the local node would leak the remote lease.
        conn = lease.get("node_conn") or self.node
        try:
            await conn.call("return_lease", lease_id=lease["lease_id"])
        except rpc.RpcError:
            pass

    async def _lease_reap_loop(self):
        import time

        while True:
            await asyncio.sleep(self._lease_idle_s / 2)
            now = time.monotonic()
            for pool in self._lease_pools.values():
                keep = []
                for lease, since in pool["free"]:
                    if now - since > self._lease_idle_s:
                        asyncio.ensure_future(self._give_back(lease))
                    else:
                        keep.append((lease, since))
                pool["free"][:] = keep

    # ----------------------------------------------------------- actors
    async def create_actor(
        self,
        cls: type,
        args: Sequence,
        kwargs: dict,
        name: str | None = None,
        resources: dict | None = None,
        detached: bool = False,
        placement: tuple | None = None,  # (node_addr, pg_id, bundle_index)
        max_concurrency: int | None = None,
        max_restarts: int = 0,
        runtime_env: dict | None = None,
        scheduling: dict | None = None,
    ):
        actor_id = ActorID.random().hex()
        if placement is None and scheduling is not None:
            reply = await self._lease_with_strategy(
                dict(resources or {"CPU": 1.0}),
                runtime_env,
                scheduling,
                actor=True,
            )
            if not reply.get("ok"):
                raise rpc.RpcError(
                    reply.get("error", "strategy actor lease failed")
                )
            node_conn = reply.get("node_conn") or self.node
        elif placement is not None:
            node_addr, pg_id, index = placement
            node_conn = (
                self.node
                if node_addr is None
                else await self._connect(node_addr)
            )
            reply = await node_conn.call(
                "lease_worker",
                resources=dict(resources or {"CPU": 1.0}),
                actor=True,
                bundle=(pg_id, index),
                runtime_env=runtime_env,
            )
        elif self.node is None:  # client mode: lease via the head
            req = dict(resources or {"CPU": 1.0})
            reply = await self._spill_lease(
                req, actor=True, runtime_env=runtime_env
            )
            node_conn = reply.get("node_conn") if reply.get("ok") else None
        else:
            node_conn = self.node
            req = dict(resources or {"CPU": 1.0})
            reply = await node_conn.call(
                "lease_worker", resources=req, actor=True,
                runtime_env=runtime_env,
            )
            if not reply.get("ok") and (
                reply.get("infeasible") or reply.get("retry_spill")
            ):
                # Same spillback as normal tasks: find a feasible node
                # via the head (and wait out autoscaler scale-up).
                reply = await self._spill_lease(
                    req, actor=True, runtime_env=runtime_env
                )
                if reply.get("ok"):
                    node_conn = reply["node_conn"]
        if not reply.get("ok"):
            raise rpc.RpcError(reply.get("error", "actor lease failed"))
        fn_id = await self.export_function(cls)
        encoded_args = self._encode_args(args, kwargs)
        conn = await self._connect(reply["addr"])
        create = await conn.call(
            "create_actor",
            actor_id=actor_id,
            fn_id=fn_id,
            args=encoded_args,
            max_concurrency=max_concurrency,
        )
        if create["status"] == "error":
            raise deserialize(create["error"])
        info = await node_conn.call("node_info")
        await self.head.call(
            "register_actor",
            actor_id=actor_id,
            name=name,
            class_name=cls.__name__,
            addr=reply["addr"],
            node_id=info["node_id"],
            detached=detached,
            # Restart spec: everything the head needs to re-create this
            # actor on a fresh worker (reference: GcsActorManager keeps
            # the creation TaskSpec for restarts, gcs_actor_manager.h:93).
            restart_spec={
                "fn_id": fn_id,
                "args": encoded_args,
                "resources": dict(resources or {"CPU": 1.0}),
                "max_concurrency": max_concurrency,
                "max_restarts": max_restarts,
                # PG-placed actors must restart on their reserved bundle.
                "placement": placement,
                "runtime_env": runtime_env,
                "scheduling": scheduling,
            },
        )
        return actor_id, reply["addr"]

    async def kill_actor(self, actor_id: str, addr: str):
        # The handle carries the birth address; a head-driven restart may
        # have moved the actor without THIS client ever seeing a failure
        # — ask the head for the authoritative address, then mark the
        # death intentional (no restart, name freed) before killing.
        addr = self._actor_addrs.get(actor_id, addr)
        try:
            info = await self.head.call("get_actor", actor_id=actor_id)
            if info.get("ok") and info.get("addr"):
                addr = info["addr"]  # head is authoritative
        except rpc.RpcError:
            pass
        try:
            await self.head.call(
                "update_actor", actor_id=actor_id, state="DEAD"
            )
        except rpc.RpcError:
            pass
        try:
            conn = await self._connect(addr)
            await conn.call("exit_worker")
        except (rpc.ConnectionLost, rpc.RpcError):
            pass

    # ------------------------------------------------- worker-side serve
    async def _handle(self, method: str, kw: dict, conn: rpc.Connection):
        ext = self.ext_handlers.get(method)
        if ext is not None:
            return await ext(conn=conn, **kw)
        fn = getattr(self, f"_on_{method}", None)
        if fn is None:
            raise rpc.RpcError(f"core_worker: unknown method {method!r}")
        return await fn(conn=conn, **rpc.tolerant_kwargs(fn, kw))

    async def _on_ping(self, conn):
        return {"ok": True}

    async def _on_get_object(self, conn, oid_hex: str):
        """Serve an object I own (reference: PushTaskReply + owner memory
        store; pull protocol object_manager.proto:60)."""
        if oid_hex not in self.memory:
            oid = ObjectID.from_hex(oid_hex)
            if self.store.contains(oid):
                return {"kind": "in_store"}
            await self._wait_local(oid_hex, timeout=None)
        kind, *rest = self.memory[oid_hex]
        if kind == "error":
            return {"kind": "error", "inband": _dumps_small(rest[0])}
        if kind == "value":
            return {"kind": "value", "inband": rest[0], "buffers": rest[1]}
        if kind == "tensor":
            return {"kind": "tensor", "meta": rest[0]}
        primary = rest[0] if rest else None
        holders = [a for a in self._locations.get(oid_hex, ()) if a != primary]
        return {"kind": "in_store", "holder": primary, "holders": holders}

    async def _on_object_location_add(self, conn, oid_hex: str, addr: str):
        """A puller cached a copy of an object we own in its node store;
        record the location so later pulls can fan in from it."""
        self._locations.setdefault(oid_hex, set()).add(addr)
        return {"ok": True}

    async def _on_object_location_remove(
        self, conn, oid_hex: str, addrs: list
    ):
        """A puller found these holders dead/evicted: prune them so the
        next resolve doesn't hand out stale sources."""
        locs = self._locations.get(oid_hex)
        if locs:
            locs.difference_update(addrs)
        return {"ok": True}

    async def _prune_locations(
        self, oid_hex: str, addrs: list, owner_conn
    ) -> None:
        if owner_conn is None:
            locs = self._locations.get(oid_hex)
            if locs:
                locs.difference_update(addrs)
            return
        try:
            await owner_conn.call(
                "object_location_remove", oid_hex=oid_hex, addrs=addrs
            )
        except (rpc.ConnectionLost, rpc.RpcError):
            pass

    async def _on_get_object_meta(self, conn, oid_hex: str):
        """Segment layout of a store-resident object (chunked pull)."""
        from ray_tpu.runtime.object_store import segment_meta

        view = self.store.get(ObjectID.from_hex(oid_hex))
        if view is None:
            return {"ok": False}
        return segment_meta(view)

    async def _on_get_object_chunk(
        self, conn, oid_hex: str, offset: int, size: int
    ):
        from ray_tpu.runtime.object_store import segment_window

        view = self.store.get(ObjectID.from_hex(oid_hex))
        if view is None:
            return {"ok": False}
        return {"ok": True, "data": segment_window(view, offset, size)}

    async def _on_generator_item(
        self, conn, task_id: str, index: int, inband, buffers, done: bool,
        attempt: int = 0,
    ):
        """Owner side of a streaming generator (reference: the owner's
        handling of ReportGeneratorItemReturns)."""
        q = self._generators.get(task_id)
        if q is None:
            return {"ok": False}  # consumer gone; producer may stop
        if attempt != self._gen_attempt.get(task_id, 0):
            return {"ok": False}  # stale report from a superseded attempt
        if done:
            q.put_nowait(("done",))
            return {"ok": True}
        oid_hex = ObjectID.for_return(TaskID.from_hex(task_id), index).hex()
        self._store_result(oid_hex, ("value", inband, buffers))
        q.put_nowait(("item", oid_hex))
        self._gen_delivered[task_id] = self._gen_delivered.get(task_id, 0) + 1
        return {"ok": True, "depth": q.qsize()}

    async def _on_generator_depth(self, conn, task_id: str):
        q = self._generators.get(task_id)
        if q is None:
            return {"ok": False}
        return {"ok": True, "depth": q.qsize()}

    async def next_generator_item(self, task_id: str):
        """("item", oid_hex) | ("done",) | ("error", exc); cleans up on
        terminal entries."""
        q = self._generators.get(task_id)
        if q is None:
            return ("done",)
        entry = await q.get()
        if entry[0] in ("done", "error"):
            del self._generators[task_id]
            self._gen_delivered.pop(task_id, None)
            self._gen_attempt.pop(task_id, None)
        return entry

    async def close_generator(self, task_id: str):
        """Abandon a streaming generator: drop undelivered items from the
        memory store and deregister, so the producer's next report gets
        ok=False and stops."""
        q = self._generators.pop(task_id, None)
        self._gen_delivered.pop(task_id, None)
        self._gen_attempt.pop(task_id, None)
        if q is None:
            return
        while not q.empty():
            entry = q.get_nowait()
            if entry[0] == "item":
                self.memory.pop(entry[1], None)

    async def _on_push_task(self, conn, spec: dict):
        fut = asyncio.get_running_loop().create_future()
        await self._exec_queue.put(("task", spec, None, fut))
        return await fut

    async def _on_actor_call(self, conn, spec: dict, actor_id: str):
        fut = asyncio.get_running_loop().create_future()
        await self._exec_queue.put(("task", spec, actor_id, fut))
        return await fut

    async def _on_create_actor(
        self, conn, actor_id: str, fn_id: str, args, max_concurrency=None
    ):
        try:
            if max_concurrency:
                self._async_sema = asyncio.Semaphore(int(max_concurrency))
            cls = await self._fetch_function(fn_id)
            a, kw = await self._decode_args(args)
            loop = asyncio.get_running_loop()
            self._actor_instance = await loop.run_in_executor(
                self._exec_pool, lambda: cls(*a, **kw)
            )
            self._actor_id = actor_id
            return {"status": "ok"}
        # tpulint: allow(broad-except reason=not swallowed - the construction error is serialized into the reply and raises at the actor handle)
        except Exception as e:
            return {"status": "error", "error": _dumps_small(_as_task_error(e))}

    async def _on_exit_worker(self, conn):
        # Process workers die hard; inproc workers (WORKER_MODE=inproc,
        # node.py _spawn_worker_inproc) install a soft stop — one
        # simulated worker must not take the host process with it.
        cb = getattr(self, "_exit_cb", None) or _hard_exit
        asyncio.get_running_loop().call_later(0.05, cb)
        return {"ok": True}

    # -------------------------------------------------- execution loop
    async def _exec_loop(self):
        """Strictly ordered execution (reference: ActorSchedulingQueue /
        NormalSchedulingQueue, task_receiver.h:43): tasks run one at a
        time, in arrival order, on the executor thread."""
        while True:
            kind, spec, actor_id, fut = await self._exec_queue.get()
            if actor_id is not None and self._is_async_method(spec):
                asyncio.ensure_future(self._run_async(spec, actor_id, fut))
                continue
            reply = await self._execute(spec, actor_id)
            if not fut.done():
                fut.set_result(reply)

    def _is_async_method(self, spec: dict) -> bool:
        name = spec["fn_id"]
        if name.startswith("@sys:") or self._actor_instance is None:
            return False
        fn = getattr(self._actor_instance, name, None)
        # Async generator methods (streaming actor calls) run concurrently
        # like coroutine methods: a long-lived token stream must not block
        # the ordered exec queue for every other caller.
        return asyncio.iscoroutinefunction(fn) or inspect.isasyncgenfunction(
            fn
        )

    async def _run_async(self, spec: dict, actor_id: str, fut):
        async with self._async_sema:
            reply = await self._execute(spec, actor_id)
        if not fut.done():
            fut.set_result(reply)

    async def _stream_generator(self, spec: dict, gen) -> dict:
        """Report a generator task's yields to the owner incrementally
        (reference: streaming generators, ReportGeneratorItemReturns in
        core_worker.proto + ObjectRefGenerator object_ref_generator.py:32).
        Awaiting each report's ack gives one-item backpressure."""
        loop = asyncio.get_running_loop()
        owner = await self._connect(spec["owner_addr"])
        task_id = spec["task_id"]
        attempt = spec.get("attempt", 0)
        index = 0
        _SENTINEL = object()
        is_async = inspect.isasyncgen(gen)

        async def _next_item():
            if is_async:
                try:
                    return await gen.__anext__()
                except StopAsyncIteration:
                    return _SENTINEL
            return await loop.run_in_executor(
                self._exec_pool, lambda: next(gen, _SENTINEL)
            )

        async def _close_gen():
            try:
                if is_async:
                    await gen.aclose()
                else:
                    getattr(gen, "close", lambda: None)()
            # tpulint: allow(broad-except reason=generator close on a consumer that already went away; there is no caller to surface it to)
            except Exception:
                pass

        while True:
            item = await _next_item()
            if item is _SENTINEL:
                break
            data = serialize(item).materialize_buffers()
            ack = await owner.call(
                "generator_item",
                task_id=task_id,
                index=index,
                inband=data.inband,
                buffers=data.buffers,
                done=False,
                attempt=attempt,
            )
            if not ack.get("ok"):
                # Consumer closed/abandoned the generator: stop producing.
                await _close_gen()
                return {"status": "ok", "results": []}
            index += 1
            # Backpressure: pause while the consumer is far behind
            # (reference: generator_backpressure_num_objects).
            while ack.get("depth", 0) >= GENERATOR_BACKPRESSURE_ITEMS:
                await asyncio.sleep(0.02)
                ack = await owner.call("generator_depth", task_id=task_id)
                if not ack.get("ok"):
                    await _close_gen()
                    return {"status": "ok", "results": []}
        await owner.call(
            "generator_item",
            task_id=task_id,
            index=index,
            inband=None,
            buffers=None,
            done=True,
            attempt=attempt,
        )
        return {"status": "ok", "results": []}

    async def _execute(self, spec: dict, actor_id: str | None) -> dict:
        from ray_tpu.util import tracing

        trace_ctx = spec.get("trace")
        with tracing.activate(trace_ctx):
            # Nested .remote() calls from the executor thread see the
            # span through a per-thread install (run_in_executor wrapper
            # in _execute_inner) — per task, so concurrent traced actor
            # tasks can't be parented to each other's spans.
            return await self._execute_inner(spec, actor_id)

    async def _execute_inner(self, spec: dict, actor_id: str | None) -> dict:
        loop = asyncio.get_running_loop()
        exec_start = time.time()
        try:
            args, kwargs = await self._decode_args(spec["args"])
            if actor_id is not None:
                method_name = spec["fn_id"]  # actor calls carry the name
                instance = self._actor_instance
                if instance is None or actor_id != self._actor_id:
                    raise ActorDiedError("no such actor in this worker")
                if method_name.startswith("@sys:"):
                    # System task: an exported function applied to the
                    # actor instance (used by compiled graphs to inject
                    # the exec loop without touching user classes).
                    sys_fn = await self._fetch_function(method_name[5:])
                    fn = functools.partial(sys_fn, instance)
                else:
                    fn = getattr(instance, method_name)
            else:
                fn = await self._fetch_function(spec["fn_id"])
            if inspect.isasyncgenfunction(fn):
                # Async generator: the object itself is the stream; it is
                # driven on the loop by _stream_generator below.
                result = fn(*args, **kwargs)
            elif asyncio.iscoroutinefunction(fn):
                result = await fn(*args, **kwargs)
            else:
                from ray_tpu.util import tracing

                trace_cur = tracing.current_context()

                def _run_sync(fn=fn, args=args, kwargs=kwargs):
                    with tracing.thread_trace(trace_cur):
                        return fn(*args, **kwargs)

                result = await loop.run_in_executor(
                    self._exec_pool, _run_sync
                )
            if spec.get("streaming"):
                if not inspect.isgenerator(result) and not inspect.isasyncgen(
                    result
                ):
                    # A coroutine method may hand back an async generator
                    # (e.g. `return self.stream(...)`) — stream it too.
                    result = iter(result)  # any other iterable streams
                reply = await self._stream_generator(spec, result)
                self.record_task_event(
                    spec, "RUNNING", ts=exec_start,
                    dur=time.time() - exec_start,
                )
                return reply
            n = spec["num_returns"]
            values = (
                [result]
                if n == 1
                else list(result)
                if n > 1
                else []
            )
            if n > 1 and len(values) != n:
                raise RayTaskError(
                    f"task declared num_returns={n} but returned "
                    f"{len(values)} values"
                )
            results = []
            task_id = TaskID.from_hex(spec["task_id"])
            if spec.get("xlang"):
                # Cross-language caller (cpp/ client): results go back
                # as plain msgpack inline — the foreign driver is the
                # owner and decodes natively; pickle never crosses the
                # language boundary (reference: cross-language
                # serialization is msgpack both ways).
                for i, value in enumerate(values):
                    oid_hex = ObjectID.for_return(task_id, i).hex()
                    try:
                        results.append(
                            (oid_hex, "xmp", rpc.pack_frame(value))
                        )
                    except (TypeError, ValueError) as e:
                        raise RayTaskError(
                            "cross-language task returned a value that "
                            f"is not msgpack-encodable: {e}"
                        ) from None
                self.record_task_event(
                    spec, "RUNNING", ts=exec_start,
                    dur=time.time() - exec_start,
                )
                return {"status": "ok", "results": results}
            transport = spec.get("tensor_transport")
            if transport and actor_id is not None:
                # Tensor transport: values stay in THIS actor's device
                # store; only location metadata enters the result path
                # (reference: gpu_object_manager — tensor_transport
                # threaded through submission, TensorTransportGetter
                # normal_task_submitter.h:101).
                for i, value in enumerate(values):
                    oid_hex = ObjectID.for_return(task_id, i).hex()
                    self.tensor_store[oid_hex] = value
                    meta = {"src_addr": self.addr, "transport": transport}
                    if isinstance(transport, str):
                        from ray_tpu import collective as col

                        if col.is_group_initialized(transport):
                            # Single-controller backends (xla_mesh) have
                            # no per-process rank: consumers then use
                            # the rpc fetch path.
                            rank = getattr(
                                col.get_group(transport), "rank", None
                            )
                            if rank is not None:
                                meta["group"] = transport
                                meta["src_rank"] = rank
                    results.append((oid_hex, "tensor", meta))
                self.record_task_event(
                    spec, "RUNNING", ts=exec_start,
                    dur=time.time() - exec_start,
                )
                return {"status": "ok", "results": results}
            for i, value in enumerate(values):
                oid = ObjectID.for_return(task_id, i)
                data = serialize(value)
                if data.total_bytes() <= INLINE_MAX_BYTES:
                    m = data.materialize_buffers()
                    results.append((oid.hex(), "inline", m.inband, m.buffers))
                else:
                    self.store.put(oid, data)
                    # Carry the holding node's address: the owner may sit
                    # on another node with a different store.
                    results.append((oid.hex(), "in_store", self.node_addr))
            self.record_task_event(
                spec, "RUNNING", ts=exec_start, dur=time.time() - exec_start
            )
            return {"status": "ok", "results": results}
        # tpulint: allow(broad-except reason=not swallowed - the error is wrapped as RayTaskError and travels to the owner in the reply)
        except Exception as e:
            # Post-mortem attach point (reference: RAY_DEBUG_POST_MORTEM,
            # util/rpdb.py): with RAY_TPU_POST_MORTEM set, the worker
            # parks at the failure frame until a debugger attaches and
            # continues; the error then travels to the owner as usual.
            # Runs on an executor thread — the accept() must not block
            # this event loop, which also answers node health RPCs.
            from ray_tpu.util.rpdb import _maybe_post_mortem

            await asyncio.get_running_loop().run_in_executor(
                None, functools.partial(_maybe_post_mortem, e.__traceback__)
            )
            self.record_task_event(
                spec, "RUNNING", ts=exec_start,
                dur=time.time() - exec_start, failed=True,
            )
            reply = {
                "status": "error",
                "error": _dumps_small(_as_task_error(e)),
            }
            if spec.get("xlang"):
                # Foreign drivers cannot unpickle: give them text too.
                reply["error_text"] = f"{type(e).__name__}: {e}"
            return reply


class ActorSubmitTarget:
    __slots__ = ("actor_id", "addr")

    def __init__(self, actor_id: str, addr: str):
        self.actor_id = actor_id
        self.addr = addr


def _dumps_small(value: Any) -> bytes:
    """Serialize fully in-band (no out-of-band buffers) — for errors and
    other payloads that must survive as a single bytes blob."""
    import cloudpickle

    try:
        return cloudpickle.dumps(value)
    # tpulint: allow(broad-except reason=unpicklable error values degrade to their repr so the reply still carries the failure)
    except Exception:
        return cloudpickle.dumps(RayTaskError(repr(value)))


def _as_task_error(e: Exception) -> Exception:
    if isinstance(e, RayTaskError):
        return e
    tb = traceback.format_exc()
    try:
        wrapped = RayTaskError(f"{type(e).__name__}: {e}\n{tb}")
        wrapped.cause = e
        return wrapped
    # tpulint: allow(broad-except reason=error wrapping must never raise; the traceback string alone still reaches the owner)
    except Exception:
        return RayTaskError(tb)


def _hard_exit():
    import os

    os._exit(0)
