"""ray_tpu.tune: hyperparameter search over trial actors.

Capability-equivalent of the reference's Tune (reference:
python/ray/tune/ — Tuner.fit → TuneController event loop over trial
actors, searchers, schedulers, ResultGrid), reduced to the surfaces the
rest of this framework uses: function and class trainables, grid/random
search, ASHA / HyperBand / median-stopping / PBT schedulers, and
TPE / Optuna / HyperOpt / BOHB searchers.

The sweep engine (``Sweep``) layers gang scheduling on top: each trial
is a JaxTrainer worker gang admitted by the memory planner + cluster
chip tables, early-stopped by ledger-driven schedulers (``LedgerASHA``),
and evolved by checkpoint-forked PBT (``LedgerPBT``).
"""

from __future__ import annotations

from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    HyperBandScheduler,
    FIFOScheduler,
    LedgerASHA,
    LedgerPBT,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.bohb_search import BOHBSearch
from ray_tpu.tune.callbacks import (
    Callback,
    JsonLoggerCallback,
    MLflowLoggerCallback,
    WandbLoggerCallback,
)
from ray_tpu.tune.hyperopt_search import HyperOptSearch
from ray_tpu.tune.optuna_search import OptunaSearch
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    Choice,
    ConcurrencyLimiter,
    Domain,
    Repeater,
    SearchAlgorithm,
    Searcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.sweep import Sweep, SweepConfig, SweepResult
from ray_tpu.tune.trial import StopTrial, Trainable, Trial
from ray_tpu.tune.tuner import (
    ResultGrid,
    RunConfig,
    TrialResult,
    TuneConfig,
    Tuner,
)

# ---------------------------------------------------------------- session
_session = None


def _set_session(s):
    global _session
    _session = s


def report(metrics: dict, checkpoint: str | None = None) -> None:
    """Report metrics from inside a function trainable (reference:
    ray.tune.report / session.report)."""
    if _session is None:
        raise RuntimeError(
            "tune.report() is only valid inside a running trial"
        )
    _session.report(metrics, checkpoint=checkpoint)


def get_checkpoint() -> str | None:
    """Checkpoint directory to restore from, if the trial was resumed
    (reference: ray.tune.get_checkpoint)."""
    if _session is None:
        raise RuntimeError(
            "tune.get_checkpoint() is only valid inside a running trial"
        )
    return _session.latest_checkpoint


__all__ = [
    "Tuner", "TuneConfig", "RunConfig", "ResultGrid", "TrialResult",
    "Trainable", "Trial", "StopTrial", "report", "get_checkpoint",
    "uniform", "loguniform", "randint", "choice", "grid_search",
    "TPESearcher", "OptunaSearch", "HyperOptSearch", "BOHBSearch",
    "ConcurrencyLimiter", "Repeater",
    "Domain", "Choice", "Searcher", "SearchAlgorithm",
    "BasicVariantGenerator",
    "TrialScheduler", "FIFOScheduler", "ASHAScheduler", "HyperBandScheduler",
    "MedianStoppingRule", "PopulationBasedTraining",
    "Sweep", "SweepConfig", "SweepResult", "LedgerASHA", "LedgerPBT",
    "Callback", "JsonLoggerCallback", "WandbLoggerCallback",
    "MLflowLoggerCallback",
]

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu('tune')
del _rlu
