"""Trial schedulers: early stopping and population-based training.

Mirrors the reference's scheduler surface (reference:
python/ray/tune/schedulers/ — ASHAScheduler async_hyperband.py,
MedianStoppingRule median_stopping_rule.py, PopulationBasedTraining
pbt.py) on the reduced Trial model in this package. Decisions are made
per reported result: CONTINUE, STOP, or (PBT) EXPLOIT.
"""

from __future__ import annotations

import math
import random
from typing import Any

CONTINUE = "CONTINUE"
STOP = "STOP"
EXPLOIT = "EXPLOIT"


class TrialScheduler:
    """Two-phase protocol: _record ingests a result, _decide returns a
    decision. The controller batch-records all results from a lockstep
    tick before deciding, so rung comparisons see every peer that
    reached the milestone in the same tick."""

    def _record(self, trial, result: dict) -> None:
        pass

    def _decide(self, trial, result: dict, trials: list) -> str:
        return CONTINUE

    def on_result(self, trial, result: dict, trials: list) -> str:
        self._record(trial, result)
        return self._decide(trial, result, trials)

    def on_batch(self, batch: list, trials: list) -> dict:
        for tr, res in batch:
            self._record(tr, res)
        return {
            tr.trial_id: self._decide(tr, res, trials) for tr, res in batch
        }

    def choose_exploit_source(self, trial, trials: list):
        return None


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Asynchronous successive halving (reference: async_hyperband.py).

    Rungs at grace_period * reduction_factor**k; a trial reaching a rung
    stops unless its metric is in the top 1/reduction_factor of results
    recorded at that rung so far.
    """

    def __init__(self, metric: str, mode: str = "max", time_attr: str =
                 "training_iteration", grace_period: int = 1,
                 reduction_factor: int = 4, max_t: int = 100):
        assert mode in ("max", "min")
        self.metric, self.mode, self.time_attr = metric, mode, time_attr
        self.grace, self.rf, self.max_t = grace_period, reduction_factor, max_t
        self._rungs: dict[int, list[float]] = {}
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(t)
            t *= reduction_factor
        self._milestones = milestones

    def _record(self, trial, result: dict) -> None:
        t = result.get(self.time_attr)
        v = result.get(self.metric)
        if t is None or v is None:
            return
        if t in self._milestones:
            self._rungs.setdefault(t, []).append(float(v))

    def _decide(self, trial, result: dict, trials: list) -> str:
        t = result.get(self.time_attr)
        v = result.get(self.metric)
        if t is None or v is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        if t in self._milestones:
            rung = self._rungs.get(t, [])
            if rung:
                k = max(1, len(rung) // self.rf)
                top = sorted(rung, reverse=(self.mode == "max"))[:k]
                worst_top = top[-1]
                good = (v >= worst_top) if self.mode == "max" else (v <= worst_top)
                if not good:
                    return STOP
        return CONTINUE


class HyperBandScheduler(TrialScheduler):
    """HyperBand (Li et al. 2018): multiple successive-halving BRACKETS
    with different exploration/exploitation trade-offs — bracket s
    starts its rung ladder at ``grace_period * reduction_factor**s``,
    so some trials get long uninterrupted budgets while others face
    aggressive early halving (reference: hyperband.py; run
    asynchronously per bracket the way the reference's
    ASHAScheduler(brackets=N) does, which fits this package's
    per-result decision seam — synchronous band barriers would need a
    PAUSE decision the Trial model deliberately omits).

    Trials are assigned to brackets round-robin at their first result.
    """

    def __init__(self, metric: str, mode: str = "max", time_attr: str =
                 "training_iteration", grace_period: int = 1,
                 reduction_factor: int = 3, max_t: int = 81,
                 num_brackets: int = 3):
        assert mode in ("max", "min")
        assert num_brackets >= 1
        self.metric, self.mode, self.time_attr = metric, mode, time_attr
        self._brackets = [
            ASHAScheduler(
                metric, mode=mode, time_attr=time_attr,
                grace_period=grace_period * reduction_factor**s,
                reduction_factor=reduction_factor, max_t=max_t,
            )
            for s in range(num_brackets)
        ]
        # Drop brackets whose first rung already exceeds max_t (they
        # would never halve — pure FIFO copies of each other).
        self._brackets = [
            b for b in self._brackets if b._milestones
        ] or self._brackets[:1]
        self._assignment: dict[str, int] = {}
        self._next = 0

    def bracket_of(self, trial) -> "ASHAScheduler":
        idx = self._assignment.get(trial.trial_id)
        if idx is None:
            idx = self._next % len(self._brackets)
            self._assignment[trial.trial_id] = idx
            self._next += 1
        return self._brackets[idx]

    def _record(self, trial, result: dict) -> None:
        self.bracket_of(trial)._record(trial, result)

    def _decide(self, trial, result: dict, trials: list) -> str:
        return self.bracket_of(trial)._decide(trial, result, trials)


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of
    other trials' running averages at the same step (reference:
    median_stopping_rule.py)."""

    def __init__(self, metric: str, mode: str = "max", time_attr: str =
                 "training_iteration", grace_period: int = 1,
                 min_samples_required: int = 3):
        self.metric, self.mode, self.time_attr = metric, mode, time_attr
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._avgs: dict[str, tuple[float, int]] = {}  # trial_id → (sum, n)

    def _record(self, trial, result: dict) -> None:
        v = result.get(self.metric)
        if v is None:
            return
        s, n = self._avgs.get(trial.trial_id, (0.0, 0))
        self._avgs[trial.trial_id] = (s + float(v), n + 1)

    def _decide(self, trial, result: dict, trials: list) -> str:
        t = result.get(self.time_attr, 0)
        v = result.get(self.metric)
        if v is None:
            return CONTINUE
        if t < self.grace:
            return CONTINUE
        others = [
            s_ / n_ for tid, (s_, n_) in self._avgs.items()
            if tid != trial.trial_id and n_ > 0
        ]
        if len(others) < self.min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        s, n = self._avgs[trial.trial_id]
        avg = s / n
        bad = (avg < median) if self.mode == "max" else (avg > median)
        return STOP if bad else CONTINUE


class LedgerASHA:
    """ASHA over the head's goodput ledger (tune/sweep.py's early
    stopper). Instead of per-result callbacks, the sweep orchestrator
    polls ``train_stats`` and feeds each trial's ledger row —
    ``(steps, value)`` where value is the folded ``loss`` (or any
    ledger field) — into :meth:`decide`. Rungs are step counts
    (``grace_period * reduction_factor**k``); a trial crossing a rung
    is stopped unless its value ranks in the top
    ``1/reduction_factor`` of everything recorded at that rung so far.
    No new reporting path: the values come from the ``train:step``
    span fold."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 grace_period: int = 2, reduction_factor: int = 4,
                 max_t: int = 10**9):
        assert mode in ("max", "min")
        self.metric, self.mode = metric, mode
        self.grace, self.rf, self.max_t = (
            grace_period, reduction_factor, max_t,
        )
        milestones = []
        t = grace_period
        while t < max_t and len(milestones) < 64:
            milestones.append(t)
            t *= reduction_factor
        self._milestones = milestones
        self._rungs: dict[int, list[float]] = {}
        # trial_id → highest milestone already judged (each rung is
        # crossed once, however often the ledger is polled).
        self._judged: dict[str, int] = {}

    def decide(self, trial_id: str, steps: int, value: float | None) -> str:
        """CONTINUE or STOP for one ledger row."""
        if steps >= self.max_t:
            return STOP
        if value is None:
            return CONTINUE
        crossed = [
            m for m in self._milestones
            if m <= steps and m > self._judged.get(trial_id, 0)
        ]
        if not crossed:
            return CONTINUE
        rung = crossed[-1]  # judge at the highest newly-crossed rung
        self._judged[trial_id] = rung
        peers = self._rungs.setdefault(rung, [])
        peers.append(float(value))
        k = max(1, len(peers) // self.rf)
        top = sorted(peers, reverse=(self.mode == "max"))[:k]
        worst_top = top[-1]
        good = (
            (value >= worst_top) if self.mode == "max"
            else (value <= worst_top)
        )
        return CONTINUE if good else STOP


class LedgerPBT:
    """Population-based training over the ledger (tune/sweep.py's fork
    driver; Jaderberg et al., arXiv:1711.09846). Every
    ``perturbation_interval`` ledger steps a bottom-quantile trial is
    stopped, its run FORKS the winner's checkpoint manifest (a
    zero-byte content-addressed copy — checkpoint/fork.py), and it
    relaunches with the winner's config perturbed."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: dict | None = None,
                 quantile_fraction: float = 0.25, seed=None):
        assert mode in ("max", "min")
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.rng = random.Random(seed)
        self._last_exploit: dict[str, int] = {}  # trial_id → steps

    def exploit_pairs(
        self, rows: dict[str, tuple[int, float | None]]
    ) -> list[tuple[str, str]]:
        """(loser, winner) pairs due for an exploit, given the current
        ledger rows {trial_id: (steps, value)}. A loser exploits at
        most once per interval window."""
        scored = [
            (v, tid) for tid, (s, v) in rows.items() if v is not None
        ]
        if len(scored) < 2:
            return []
        scored.sort(key=lambda x: x[0], reverse=(self.mode == "max"))
        k = max(1, int(len(scored) * self.quantile))
        winners = [tid for _, tid in scored[:k]]
        losers = {tid for _, tid in scored[-k:]}
        out = []
        for tid, (steps, v) in rows.items():
            if tid not in losers or v is None:
                continue
            if steps - self._last_exploit.get(tid, 0) < self.interval:
                continue
            cands = [w for w in winners if w != tid]
            if not cands:
                continue
            self._last_exploit[tid] = steps
            out.append((tid, self.rng.choice(cands)))
        return out

    def perturb(self, config: dict) -> dict:
        out = dict(config)
        for key, spec in self.mutations.items():
            if callable(spec):
                out[key] = spec()
            elif isinstance(spec, list):
                out[key] = self.rng.choice(spec)
            else:  # numeric: jitter
                factor = self.rng.choice([0.8, 1.2])
                out[key] = out.get(key, spec) * factor
        return out


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: pbt.py): every perturbation_interval steps, a
    bottom-quantile trial clones a top-quantile trial's checkpoint and
    perturbs its hyperparameters (resample or *1.2 / *0.8)."""

    def __init__(self, metric: str, mode: str = "max", time_attr: str =
                 "training_iteration", perturbation_interval: int = 5,
                 hyperparam_mutations: dict | None = None,
                 quantile_fraction: float = 0.25, seed=None):
        self.metric, self.mode, self.time_attr = metric, mode, time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.rng = random.Random(seed)
        self._last: dict[str, float] = {}  # trial_id → last metric

    def _record(self, trial, result: dict) -> None:
        v = result.get(self.metric)
        if v is not None:
            self._last[trial.trial_id] = float(v)

    def _decide(self, trial, result: dict, trials: list) -> str:
        t = result.get(self.time_attr, 0)
        if t == 0 or t % self.interval != 0:
            return CONTINUE
        scored = [
            (self._last[tr.trial_id], tr) for tr in trials
            if tr.trial_id in self._last
        ]
        if len(scored) < 2:
            return CONTINUE
        scored.sort(key=lambda x: x[0], reverse=(self.mode == "max"))
        k = max(1, int(len(scored) * self.quantile))
        bottom_ids = {tr.trial_id for _, tr in scored[-k:]}
        if trial.trial_id in bottom_ids:
            return EXPLOIT
        return CONTINUE

    def choose_exploit_source(self, trial, trials: list):
        scored = [
            (self._last[tr.trial_id], tr) for tr in trials
            if tr.trial_id in self._last and tr.trial_id != trial.trial_id
        ]
        if not scored:
            return None
        scored.sort(key=lambda x: x[0], reverse=(self.mode == "max"))
        k = max(1, int(len(scored) * self.quantile))
        return self.rng.choice([tr for _, tr in scored[:k]])

    def perturb(self, config: dict) -> dict:
        out = dict(config)
        for key, spec in self.mutations.items():
            if callable(spec):
                out[key] = spec()
            elif isinstance(spec, list):
                out[key] = self.rng.choice(spec)
            else:  # numeric: jitter
                factor = self.rng.choice([0.8, 1.2])
                out[key] = out.get(key, spec) * factor
        return out
