"""BOHB search: Bayesian-optimized HyperBand suggestions (Falkner,
Klein & Hutter 2018) over the Tune Searcher seam.

Reference adapter: python/ray/tune/search/bohb/bohb_search.py:1
(TuneBOHB) wraps hpbandster's BOHB config generator and pairs with the
HyperBandForBOHB scheduler. hpbandster is not in this image (and is
unmaintained), so the KDE machinery is implemented natively here —
the same mechanics the paper and hpbandster use:

- Observations are bucketed by BUDGET (the ``time_attr`` value a trial
  reached before completing or being stopped by the scheduler —
  pairing with :class:`ray_tpu.tune.schedulers.ASHAScheduler` gives
  the successive-halving budget ladder).
- The model uses the HIGHEST budget with at least
  ``min_points_in_model`` observations; the good/bad split is at the
  top ``gamma`` quantile.
- A suggestion draws ``num_candidates`` samples around good
  observations (diagonal Gaussian KDE, log-space for log domains) and
  keeps the one maximizing l(x)/g(x); with probability
  ``random_fraction`` (and before the model has data) it samples the
  prior instead — BOHB's guaranteed-exploration floor.
"""

from __future__ import annotations

import math
import random
from typing import Any

from ray_tpu.tune.search import (
    Choice,
    Domain,
    LogUniform,
    RandInt,
    Searcher,
    Uniform,
)


class BOHBSearch(Searcher):
    """Model-based suggestions with multi-fidelity observation buckets.

    param_space uses this package's Domain objects (uniform,
    loguniform, randint, choice) or plain constants; grid_search axes
    are not supported (use BasicVariantGenerator), matching the
    reference adapter.
    """

    def __init__(
        self,
        param_space: dict,
        *,
        metric: str = "loss",
        mode: str = "min",
        time_attr: str = "training_iteration",
        min_points_in_model: int | None = None,
        gamma: float = 0.25,
        num_candidates: int = 24,
        random_fraction: float = 1 / 3,
        bandwidth_factor: float = 3.0,
        seed=None,
    ):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.gamma = gamma
        self.num_candidates = num_candidates
        self.random_fraction = random_fraction
        self.bandwidth_factor = bandwidth_factor
        self._rng = random.Random(seed)
        self._constants: dict[str, Any] = {}
        self._domains: dict[str, Domain] = {}
        for name, dom in param_space.items():
            if isinstance(dom, dict) and "grid_search" in dom:
                raise ValueError(
                    "BOHBSearch does not expand grid_search axes; use "
                    "BasicVariantGenerator"
                )
            if isinstance(dom, Domain):
                self._domains[name] = dom
            else:
                self._constants[name] = dom
        self.min_points_in_model = (
            max(len(self._domains) + 1, 3)
            if min_points_in_model is None
            else min_points_in_model
        )
        # budget → list[(params, objective)], objective minimized.
        self._by_budget: dict[float, list[tuple[dict, float]]] = {}
        self._ongoing: dict[str, dict] = {}

    # ------------------------------------------------------- sampling
    def _sample_prior(self) -> dict:
        return {
            name: dom.sample(self._rng)
            for name, dom in self._domains.items()
        }

    def _model_budget(self) -> float | None:
        """Highest budget with enough observations (BOHB's rule: the
        most informative fidelity that can support a model)."""
        eligible = [
            b
            for b, obs in self._by_budget.items()
            if len(obs) >= self.min_points_in_model
        ]
        return max(eligible) if eligible else None

    def _split(self, obs: list) -> tuple[list, list]:
        ordered = sorted(obs, key=lambda pv: pv[1])
        n_good = max(self.min_points_in_model - 1,
                     int(math.ceil(self.gamma * len(ordered))))
        n_good = min(n_good, len(ordered) - 1) or 1
        return ordered[:n_good], ordered[n_good:]

    def _bandwidth(self, dom, values: list) -> float:
        lo, hi = self._bounds(dom)
        spread = (hi - lo) or 1.0
        if len(values) > 1:
            mean = sum(values) / len(values)
            var = sum((v - mean) ** 2 for v in values) / (
                len(values) - 1
            )
            sigma = math.sqrt(var)
        else:
            sigma = 0.0
        return max(sigma, spread / 20.0)

    def _bounds(self, dom) -> tuple[float, float]:
        if isinstance(dom, LogUniform):
            return math.log(dom.low), math.log(dom.high)
        if isinstance(dom, (Uniform, RandInt)):
            return float(dom.low), float(dom.high)
        return 0.0, 1.0

    def _to_cont(self, dom, v) -> float:
        return math.log(v) if isinstance(dom, LogUniform) else float(v)

    def _from_cont(self, dom, x: float):
        if isinstance(dom, LogUniform):
            return min(dom.high, max(dom.low, math.exp(x)))
        if isinstance(dom, RandInt):
            return int(min(dom.high, max(dom.low, round(x))))
        if isinstance(dom, Uniform):
            return min(dom.high, max(dom.low, x))
        return x

    def _kde_logpdf(self, dom, x: float, values: list, bw: float) -> float:
        if not values:
            return 0.0
        acc = 0.0
        for v in values:
            acc += math.exp(-0.5 * ((x - v) / bw) ** 2)
        return math.log(acc / (len(values) * bw) + 1e-300)

    def _choice_logpmf(self, choices, v, values: list) -> float:
        # Add-one-smoothed categorical frequency.
        count = sum(1 for o in values if o == v)
        return math.log((count + 1) / (len(values) + len(choices)))

    def _sample_model(self, obs: list) -> dict:
        good, bad = self._split(obs)
        # Candidate-independent projections and bandwidths, hoisted out
        # of the num_candidates loop (they scale with observation
        # count; recomputing 24x per suggest is pure waste).
        per_dom: dict[str, tuple] = {}
        for name, dom in self._domains.items():
            if isinstance(dom, Choice):
                per_dom[name] = (
                    [p[name] for p, _ in good],
                    [p[name] for p, _ in bad],
                    None,
                    None,
                )
            else:
                gvals = [self._to_cont(dom, p[name]) for p, _ in good]
                bvals = [self._to_cont(dom, p[name]) for p, _ in bad]
                per_dom[name] = (
                    gvals,
                    bvals,
                    self._bandwidth(dom, gvals),
                    self._bandwidth(dom, bvals),
                )
        best_params, best_score = None, -math.inf
        for _ in range(self.num_candidates):
            seed_params, _ = self._rng.choice(good)
            cand: dict = {}
            score = 0.0
            for name, dom in self._domains.items():
                gvals, bvals, bw_g, bw_b = per_dom[name]
                if isinstance(dom, Choice):
                    v = self._rng.choice(
                        gvals if self._rng.random() < 0.8
                        else list(dom.categories)
                    )
                    cand[name] = v
                    score += self._choice_logpmf(
                        dom.categories, v, gvals
                    ) - self._choice_logpmf(dom.categories, v, bvals)
                    continue
                center = self._to_cont(dom, seed_params[name])
                x = self._rng.gauss(
                    center, bw_g * self.bandwidth_factor
                )
                cand[name] = self._from_cont(dom, x)
                x = self._to_cont(dom, cand[name])
                score += self._kde_logpdf(
                    dom, x, gvals, bw_g
                ) - self._kde_logpdf(dom, x, bvals, bw_b)
            if score > best_score:
                best_params, best_score = cand, score
        return best_params or self._sample_prior()

    # ---------------------------------------------------- Searcher API
    def suggest(self, trial_id: str) -> dict | None:
        budget = self._model_budget()
        if budget is None or self._rng.random() < self.random_fraction:
            params = self._sample_prior()
        else:
            params = self._sample_model(self._by_budget[budget])
        config = {**self._constants, **params}
        self._ongoing[trial_id] = params
        return config

    def on_trial_complete(self, trial_id: str, result: dict | None):
        params = self._ongoing.pop(trial_id, None)
        if params is None or not result or self.metric not in result:
            return
        value = float(result[self.metric])
        if self.mode == "max":
            value = -value
        budget = float(result.get(self.time_attr, 1))
        self._by_budget.setdefault(budget, []).append((params, value))
