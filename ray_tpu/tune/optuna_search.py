"""OptunaSearch: drive Tune trials from an optuna study.

Mirrors the reference adapter (reference:
python/ray/tune/search/optuna/optuna_search.py:1 OptunaSearch — convert
the Tune search space to optuna distributions, study.ask() per suggest,
study.tell() per completion) over this package's Searcher seam
(tune/search.py). When optuna is not installed, a faithful in-module
fake implements the same ask/tell study surface (create_study,
FloatDistribution/IntDistribution/CategoricalDistribution, Trial) so
the adapter code path — space conversion, trial bookkeeping, direction
mapping — is identical and testable either way; with optuna on the
path, its real TPE sampler drives the suggestions.
"""

from __future__ import annotations

import math
import random
from typing import Any

from ray_tpu.tune.search import (
    Choice,
    Domain,
    LogUniform,
    RandInt,
    Searcher,
    Uniform,
)


# --------------------------------------------------------------- fake
# Minimal optuna surface: enough of study.ask/tell for the adapter.
# Sampling is TPE-flavored (split observations at the median, sample
# near a good observation) so the fake's behavior is directionally
# faithful, not just random.
class _FloatDistribution:
    def __init__(self, low: float, high: float, log: bool = False):
        self.low, self.high, self.log = low, high, log


class _IntDistribution:
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high


class _CategoricalDistribution:
    def __init__(self, choices):
        self.choices = list(choices)


class _FakeTrial:
    def __init__(self, number: int, params: dict):
        self.number = number
        self.params = params


class _FakeStudy:
    def __init__(self, direction: str, seed=None):
        self.direction = direction
        self._rng = random.Random(seed)
        self._trials: dict[int, _FakeTrial] = {}
        self._told: list[tuple[dict, float]] = []
        self._n = 0
        self.best_trial: _FakeTrial | None = None
        self._best_value = math.inf

    def _sample(self, name: str, dist) -> Any:
        good = self._good_observations(name)
        if good and self._rng.random() < 0.7:
            # Perturb a good observation (TPE-flavored exploitation).
            base = self._rng.choice(good)
            if isinstance(dist, _CategoricalDistribution):
                return base
            if isinstance(dist, _IntDistribution):
                span = max(1, (dist.high - dist.low) // 8)
                return min(
                    dist.high,
                    max(dist.low, base + self._rng.randint(-span, span)),
                )
            lo, hi = dist.low, dist.high
            if dist.log:
                lo, hi, base = math.log(lo), math.log(hi), math.log(base)
            sigma = (hi - lo) / 10
            x = self._rng.gauss(base, sigma)
            if dist.log:
                x = math.exp(x)
            # Clamp in ORIGINAL space: exp(log(high)) can exceed high.
            return min(dist.high, max(dist.low, x))
        if isinstance(dist, _CategoricalDistribution):
            return self._rng.choice(dist.choices)
        if isinstance(dist, _IntDistribution):
            return self._rng.randint(dist.low, dist.high)
        if dist.log:
            x = math.exp(
                self._rng.uniform(math.log(dist.low), math.log(dist.high))
            )
            return min(dist.high, max(dist.low, x))
        return self._rng.uniform(dist.low, dist.high)

    def _good_observations(self, name: str) -> list:
        if len(self._told) < 4:
            return []
        ordered = sorted(
            self._told,
            key=lambda pv: pv[1],
            reverse=(self.direction == "maximize"),
        )
        # TPE-style gamma: the good set is the top quartile.
        top = ordered[: max(1, len(ordered) // 4)]
        return [p[name] for p, _ in top if name in p]

    def ask(self, distributions: dict) -> _FakeTrial:
        params = {
            name: self._sample(name, dist)
            for name, dist in distributions.items()
        }
        trial = _FakeTrial(self._n, params)
        self._trials[self._n] = trial
        self._n += 1
        return trial

    def tell(self, trial: _FakeTrial, value: float) -> None:
        self._told.append((trial.params, value))
        key = -value if self.direction == "maximize" else value
        if key < self._best_value:
            self._best_value = key
            self.best_trial = trial


class _FakeOptuna:
    FloatDistribution = _FloatDistribution
    IntDistribution = _IntDistribution
    CategoricalDistribution = _CategoricalDistribution

    @staticmethod
    def create_study(direction: str = "minimize", sampler=None, seed=None):
        return _FakeStudy(direction, seed=seed)


def _load_optuna(force_fake: bool):
    if force_fake:
        return _FakeOptuna, True
    try:
        import optuna  # noqa: PLC0415

        return optuna, False
    except ImportError:
        return _FakeOptuna, True


# ------------------------------------------------------------ adapter
class OptunaSearch(Searcher):
    """Suggest Tune configs from an optuna study (ask/tell protocol).

    param_space uses this package's Domain objects (uniform, loguniform,
    randint, choice) or plain constants; grid_search axes are not
    supported here (use BasicVariantGenerator for grids), matching the
    reference adapter's behavior.
    """

    def __init__(
        self,
        param_space: dict,
        *,
        metric: str = "loss",
        mode: str = "min",
        seed=None,
        _force_fake: bool = False,
    ):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self._optuna, self.using_fake = _load_optuna(_force_fake)
        self.metric = metric
        self.mode = mode
        self._constants: dict[str, Any] = {}
        self._distributions: dict[str, Any] = {}
        for name, dom in param_space.items():
            if isinstance(dom, dict) and "grid_search" in dom:
                raise ValueError(
                    "OptunaSearch does not expand grid_search axes; "
                    "use BasicVariantGenerator"
                )
            converted = self._convert(dom)
            if converted is None:
                self._constants[name] = dom
            else:
                self._distributions[name] = converted
        direction = "minimize" if mode == "min" else "maximize"
        if self.using_fake:
            self._study = self._optuna.create_study(
                direction=direction, seed=seed
            )
        else:
            sampler = self._optuna.samplers.TPESampler(seed=seed)
            self._study = self._optuna.create_study(
                direction=direction, sampler=sampler
            )
        self._ongoing: dict[str, Any] = {}  # tune trial_id → optuna trial

    def _convert(self, dom):
        o = self._optuna
        if isinstance(dom, Uniform):
            return o.FloatDistribution(dom.low, dom.high)
        if isinstance(dom, LogUniform):
            return o.FloatDistribution(dom.low, dom.high, log=True)
        if isinstance(dom, RandInt):
            # Our randint is exclusive-high; optuna's is inclusive.
            return o.IntDistribution(dom.low, dom.high - 1)
        if isinstance(dom, Choice):
            return o.CategoricalDistribution(dom.categories)
        if isinstance(dom, Domain):
            raise ValueError(
                f"cannot convert {type(dom).__name__} to an optuna "
                "distribution"
            )
        return None  # constant

    def suggest(self, trial_id: str) -> dict | None:
        trial = self._study.ask(self._distributions)
        self._ongoing[trial_id] = trial
        return {**self._constants, **trial.params}

    def on_trial_complete(self, trial_id: str, result: dict | None):
        trial = self._ongoing.pop(trial_id, None)
        if trial is None:
            return
        if result is None or self.metric not in result:
            # Every asked trial must reach a terminal state, or real
            # optuna accumulates RUNNING phantoms across a long sweep.
            if not self.using_fake:
                self._study.tell(
                    trial, state=self._optuna.trial.TrialState.FAIL
                )
            return
        self._study.tell(trial, float(result[self.metric]))

    @property
    def best_params(self) -> dict | None:
        try:
            best = self._study.best_trial
        except ValueError:
            # Real optuna raises when no trial has completed yet.
            return None
        return None if best is None else {**self._constants, **best.params}
