"""HyperOptSearch: drive Tune trials from hyperopt's TPE.

Mirrors the reference adapter (reference:
python/ray/tune/search/hyperopt/hyperopt_search.py:1 HyperOptSearch —
convert the Tune space to hp.* expressions, drive tpe.suggest against a
hyperopt Trials book manually, attach losses on completion) over this
package's Searcher seam. When hyperopt is not installed, the adapter
runs on the same in-module fake study engine OptunaSearch uses
(optuna_search._FakeStudy — ask/tell with TPE-flavored sampling), so
the space conversion and trial bookkeeping are exercised either way.

hp.choice indices: hyperopt reports categorical picks as INDICES into
the choice list; this adapter maps them back to the category values,
like the reference does.
"""

from __future__ import annotations

from typing import Any

from ray_tpu.tune.optuna_search import (
    _CategoricalDistribution,
    _FakeStudy,
    _FloatDistribution,
    _IntDistribution,
)
from ray_tpu.tune.search import (
    Choice,
    Domain,
    LogUniform,
    RandInt,
    Searcher,
    Uniform,
)


def _load_hyperopt(force_fake: bool):
    if force_fake:
        return None, True
    try:
        import hyperopt  # noqa: PLC0415

        return hyperopt, False
    except ImportError:
        return None, True


class HyperOptSearch(Searcher):
    """Suggest Tune configs from hyperopt TPE (or the fake engine).

    param_space uses this package's Domain objects or constants;
    grid_search axes are rejected like the reference adapter.
    """

    def __init__(
        self,
        param_space: dict,
        *,
        metric: str = "loss",
        mode: str = "min",
        seed=None,
        _force_fake: bool = False,
    ):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self._hp, self.using_fake = _load_hyperopt(_force_fake)
        self.metric = metric
        self.mode = mode
        self._seed = seed
        self._constants: dict[str, Any] = {}
        self._domains: dict[str, Domain] = {}
        for name, dom in param_space.items():
            if isinstance(dom, dict) and "grid_search" in dom:
                raise ValueError(
                    "HyperOptSearch does not expand grid_search axes; "
                    "use BasicVariantGenerator"
                )
            if isinstance(dom, Domain):
                self._domains[name] = dom
            else:
                self._constants[name] = dom
        self._ongoing: dict[str, Any] = {}  # tune trial_id → book entry
        if self.using_fake:
            self._study = _FakeStudy(
                "minimize" if mode == "min" else "maximize", seed=seed
            )
            self._fake_dists = {
                name: self._fake_dist(dom)
                for name, dom in self._domains.items()
            }
        else:
            self._space = {
                name: self._hp_expr(name, dom)
                for name, dom in self._domains.items()
            }
            self._trials = self._hp.Trials()
            self._hp_domain = self._hp.base.Domain(
                lambda spec: 0, self._space
            )
            # An unseeded searcher must explore differently per run
            # (the fake path's random.Random(None) already does).
            import random as _random

            self._next_seed = (
                seed if seed is not None else _random.randrange(1 << 30)
            )

    # ------------------------------------------------------ conversion
    @staticmethod
    def _fake_dist(dom: Domain):
        if isinstance(dom, Uniform):
            return _FloatDistribution(dom.low, dom.high)
        if isinstance(dom, LogUniform):
            return _FloatDistribution(dom.low, dom.high, log=True)
        if isinstance(dom, RandInt):
            return _IntDistribution(dom.low, dom.high - 1)
        if isinstance(dom, Choice):
            return _CategoricalDistribution(dom.categories)
        raise ValueError(
            f"cannot convert {type(dom).__name__} for hyperopt"
        )

    def _hp_expr(self, name: str, dom: Domain):
        import math

        hp = self._hp.hp
        if isinstance(dom, Uniform):
            return hp.uniform(name, dom.low, dom.high)
        if isinstance(dom, LogUniform):
            return hp.loguniform(name, math.log(dom.low), math.log(dom.high))
        if isinstance(dom, RandInt):
            return dom.low + hp.randint(name, dom.high - dom.low)
        if isinstance(dom, Choice):
            return hp.choice(name, dom.categories)
        raise ValueError(
            f"cannot convert {type(dom).__name__} to an hp expression"
        )

    # -------------------------------------------------------- protocol
    def suggest(self, trial_id: str) -> dict | None:
        if self.using_fake:
            trial = self._study.ask(self._fake_dists)
            self._ongoing[trial_id] = trial
            return {**self._constants, **trial.params}

        new_ids = self._trials.new_trial_ids(1)
        self._next_seed += 1
        docs = self._hp.tpe.suggest(
            new_ids, self._hp_domain, self._trials, self._next_seed
        )
        self._trials.insert_trial_docs(docs)
        self._trials.refresh()
        doc = docs[0]
        # Keep the doc itself: completion marks it in place (O(1), no
        # linear scan of the trials book).
        self._ongoing[trial_id] = doc
        return {**self._constants, **self._params_from_vals(doc)}

    def _params_from_vals(self, doc) -> dict:
        """misc.vals carries hyperopt's RAW labels: choice picks are
        indices into the category list, randint values are 0-based
        regardless of the dom.low offset applied in the expression —
        both must be decoded back to user-space values."""
        vals = {k: v[0] for k, v in doc["misc"]["vals"].items() if v}
        params = {}
        for name, dom in self._domains.items():
            v = vals[name]
            if isinstance(dom, Choice):
                v = dom.categories[int(v)]
            elif isinstance(dom, RandInt):
                v = int(v) + dom.low
            params[name] = v
        return params

    def on_trial_complete(self, trial_id: str, result: dict | None):
        entry = self._ongoing.pop(trial_id, None)
        if entry is None:
            return
        failed = result is None or self.metric not in result
        if self.using_fake:
            if not failed:
                self._study.tell(entry, float(result[self.metric]))
            return
        value = None if failed else float(result[self.metric])
        if value is not None and self.mode == "max":
            value = -value  # hyperopt minimizes
        doc = entry
        if failed:
            doc["state"] = self._hp.JOB_STATE_ERROR
            doc["result"] = {"status": self._hp.STATUS_FAIL}
        else:
            doc["state"] = self._hp.JOB_STATE_DONE
            doc["result"] = {
                "loss": value,
                "status": self._hp.STATUS_OK,
            }
        self._trials.refresh()

    @property
    def best_params(self) -> dict | None:
        if self.using_fake:
            best = self._study.best_trial
            return (
                None
                if best is None
                else {**self._constants, **best.params}
            )
        done = [
            t
            for t in self._trials.trials
            if t["state"] == self._hp.JOB_STATE_DONE
        ]
        if not done:
            return None
        best = min(done, key=lambda t: t["result"]["loss"])
        return {**self._constants, **self._params_from_vals(best)}
