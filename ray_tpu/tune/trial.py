"""Trial model + the actor that hosts one trial.

Mirrors the reference's Trial/trainable split (reference:
python/ray/tune/experiment/trial.py Trial states; trainable API
python/ray/tune/trainable/ — function trainables report via session,
class trainables implement step/save/restore). The trial actor runs
function trainables on a private thread so the controller can poll and
stop them through ordinary actor calls.
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
import time
import traceback
from typing import Any, Callable

import ray_tpu

logger = logging.getLogger("ray_tpu.tune")

PENDING = "PENDING"
RUNNING = "RUNNING"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


class Trial:
    def __init__(self, trial_id: str, config: dict, local_dir: str):
        self.trial_id = trial_id
        self.config = config
        self.local_dir = local_dir
        self.status = PENDING
        self.results: list[dict] = []
        self.last_result: dict = {}
        self.checkpoint: str | None = None
        self.error: str | None = None
        self.actor = None
        self.is_class_api = False
        self.iteration = 0
        # Infra-failure retry counter (budgeted by TUNE_INFRA_RETRIES;
        # preemptions restart for free and don't consume it).
        self.infra_retries = 0

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status}, iters={self.iteration})"


class Trainable:
    """Class-API trainable (reference: tune/trainable/trainable.py):
    subclass and implement setup/step/save_checkpoint/load_checkpoint."""

    def setup(self, config: dict) -> None:
        pass

    def step(self) -> dict:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def cleanup(self) -> None:
        pass


class StopTrial(Exception):
    pass


class _FnSession:
    """In-actor session for function trainables: buffers reports, carries
    the stop flag the controller sets (reference: tune function API
    session + StopTrial semantics)."""

    def __init__(self, trial_dir: str):
        self.lock = threading.Lock()
        self.reports: list[dict] = []
        self.stop = False
        self.trial_dir = trial_dir
        self.n_ckpt = 0
        self.latest_checkpoint: str | None = None

    def report(self, metrics: dict, checkpoint: str | None = None):
        with self.lock:
            if self.stop:
                raise StopTrial()
            entry = {"metrics": dict(metrics)}
            if checkpoint is not None:
                dst = os.path.join(self.trial_dir, f"checkpoint_{self.n_ckpt:06d}")
                self.n_ckpt += 1
                shutil.copytree(checkpoint, dst, dirs_exist_ok=True)
                entry["checkpoint"] = dst
                self.latest_checkpoint = dst
            self.reports.append(entry)


@ray_tpu.remote
class TrialActor:
    """Hosts one trial (reference: tune trials are remote trainable
    actors driven by TuneController)."""

    def __init__(self, trial_dir: str):
        os.makedirs(trial_dir, exist_ok=True)
        self.trial_dir = trial_dir
        self.session = _FnSession(trial_dir)
        self.thread: threading.Thread | None = None
        self.done = False
        self.error: str | None = None
        self.instance: Trainable | None = None
        self.iteration = 0

    # ------------------------------------------------- function API path
    def start_fn(self, fn: Callable, config: dict, restore: str | None = None):
        import ray_tpu.tune as tune_mod

        self.session.latest_checkpoint = restore

        def run():
            tune_mod._set_session(self.session)
            try:
                fn(dict(config))
            except StopTrial:
                pass
            except Exception:  # noqa: BLE001 - reported via poll
                self.error = traceback.format_exc()
                logger.warning("trial failed:\n%s", self.error)
            finally:
                self.done = True
                tune_mod._set_session(None)

        self.thread = threading.Thread(target=run, daemon=True)
        self.thread.start()
        return True

    def poll(self):
        with self.session.lock:
            reports = self.session.reports
            self.session.reports = []
        return {
            "reports": reports,
            "done": self.done,
            "error": self.error,
            "checkpoint": self.session.latest_checkpoint,
        }

    def stop_fn(self):
        with self.session.lock:
            self.session.stop = True
        return True

    # ---------------------------------------------------- class API path
    def setup_class(self, cls: type, config: dict, restore: str | None = None):
        self.instance = cls()
        self.instance.setup(dict(config))
        if restore:
            self.instance.load_checkpoint(restore)
        return True

    def train_step(self):
        assert self.instance is not None
        self.iteration += 1
        metrics = self.instance.step()
        metrics.setdefault("training_iteration", self.iteration)
        return metrics

    def save(self):
        assert self.instance is not None
        d = os.path.join(self.trial_dir, f"checkpoint_{self.iteration:06d}")
        os.makedirs(d, exist_ok=True)
        self.instance.save_checkpoint(d)
        return d

    def restore(self, checkpoint_dir: str, config: dict | None = None,
                iteration: int | None = None):
        assert self.instance is not None
        if config is not None:
            self.instance.setup(dict(config))
        self.instance.load_checkpoint(checkpoint_dir)
        if iteration is not None:
            self.iteration = iteration
        return True

    def shutdown(self):
        if self.instance is not None:
            self.instance.cleanup()
        return True
