"""Search spaces and suggestion algorithms.

Mirrors the reference's tune.search surface (reference:
python/ray/tune/search/ — sample.py distributions, grid_search,
BasicVariantGenerator basic_variant.py) in reduced form: distribution
objects + a variant generator that expands grid axes and samples the
rest; pluggable Searcher ABC for smarter algorithms.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math

        self.lo, self.hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.lo, self.hi))


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def grid_search(values) -> dict:
    return {"grid_search": list(values)}


class Searcher:
    """ABC (reference: tune/search/searcher.py Searcher)."""

    def suggest(self, trial_id: str) -> dict | None:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: dict | None):
        pass


class BasicVariantGenerator(Searcher):
    """Expand grid_search axes into a cross product; sample Domain leaves
    num_samples times (reference: basic_variant.py semantics)."""

    def __init__(self, param_space: dict, num_samples: int = 1, seed=None):
        self.rng = random.Random(seed)
        grid_axes: list[tuple[str, list]] = []
        for k, v in param_space.items():
            if isinstance(v, dict) and set(v.keys()) == {"grid_search"}:
                grid_axes.append((k, v["grid_search"]))
        self.param_space = param_space
        if grid_axes:
            keys = [k for k, _ in grid_axes]
            combos = list(itertools.product(*[vals for _, vals in grid_axes]))
            self._grid = [dict(zip(keys, c)) for c in combos]
        else:
            self._grid = [{}]
        self._queue = [
            (g, s) for s in range(num_samples) for g in self._grid
        ]
        self._i = 0

    @property
    def total(self) -> int:
        return len(self._queue)

    def suggest(self, trial_id: str) -> dict | None:
        if self._i >= len(self._queue):
            return None
        grid_part, _ = self._queue[self._i]
        self._i += 1
        config = {}
        for k, v in self.param_space.items():
            if k in grid_part:
                config[k] = grid_part[k]
            elif isinstance(v, Domain):
                config[k] = v.sample(self.rng)
            elif isinstance(v, dict) and set(v.keys()) == {"grid_search"}:
                pass  # handled via grid_part
            else:
                config[k] = v
        config.update(grid_part)
        return config
