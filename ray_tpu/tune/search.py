"""Search spaces and suggestion algorithms.

Mirrors the reference's tune.search surface (reference:
python/ray/tune/search/ — sample.py distributions, grid_search,
BasicVariantGenerator basic_variant.py) in reduced form: distribution
objects + a variant generator that expands grid axes and samples the
rest; pluggable Searcher ABC for smarter algorithms.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Protocol, runtime_checkable


@runtime_checkable
class SearchAlgorithm(Protocol):
    """Structural contract every search backend speaks — the native
    Searcher subclasses here and the legacy wrappers (bohb_search /
    hyperopt_search / optuna_search) alike. ``suggest`` returns a
    config dict, ``None`` (exhausted), or the DEFER sentinel (ask again
    later); ``on_trial_complete`` feeds the observation back."""

    def suggest(self, trial_id: str) -> Any: ...

    def on_trial_complete(
        self, trial_id: str, result: dict | None
    ) -> None: ...


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math

        self.low, self.high = low, high
        self.lo, self.hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.lo, self.hi))


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def grid_search(values) -> dict:
    return {"grid_search": list(values)}


# Sentinel: "no suggestion right now, ask again later" — distinct from
# None ("search space exhausted"). Reference: ConcurrencyLimiter defers
# suggestions without finishing the search (tune/search/concurrency_limiter.py).
DEFER = object()


class Searcher:
    """ABC (reference: tune/search/searcher.py Searcher)."""

    def suggest(self, trial_id: str) -> dict | None:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: dict | None):
        pass


class BasicVariantGenerator(Searcher):
    """Expand grid_search axes into a cross product; sample Domain leaves
    num_samples times (reference: basic_variant.py semantics)."""

    def __init__(self, param_space: dict, num_samples: int = 1, seed=None):
        self.rng = random.Random(seed)
        grid_axes: list[tuple[str, list]] = []
        for k, v in param_space.items():
            if isinstance(v, dict) and set(v.keys()) == {"grid_search"}:
                grid_axes.append((k, v["grid_search"]))
        self.param_space = param_space
        if grid_axes:
            keys = [k for k, _ in grid_axes]
            combos = list(itertools.product(*[vals for _, vals in grid_axes]))
            self._grid = [dict(zip(keys, c)) for c in combos]
        else:
            self._grid = [{}]
        self._queue = [
            (g, s) for s in range(num_samples) for g in self._grid
        ]
        self._i = 0

    @property
    def total(self) -> int:
        return len(self._queue)

    def suggest(self, trial_id: str) -> dict | None:
        if self._i >= len(self._queue):
            return None
        grid_part, _ = self._queue[self._i]
        self._i += 1
        config = {}
        for k, v in self.param_space.items():
            if k in grid_part:
                config[k] = grid_part[k]
            elif isinstance(v, Domain):
                config[k] = v.sample(self.rng)
            elif isinstance(v, dict) and set(v.keys()) == {"grid_search"}:
                pass  # handled via grid_part
            else:
                config[k] = v
        config.update(grid_part)
        return config


class TPESearcher(Searcher):
    """Tree-structured-Parzen-Estimator-style Bayesian search over the
    Domain types (the native replacement for the reference's hyperopt /
    optuna integrations, tune/search/hyperopt, tune/search/optuna —
    both of which default to TPE samplers).

    After ``n_initial`` random trials, observations split into a good
    quantile (gamma) and the rest; candidates are sampled from a kernel
    density fit to the good configs and ranked by the density ratio
    l_good/l_bad, exactly TPE's acquisition.
    """

    def __init__(
        self,
        param_space: dict,
        metric: str,
        mode: str = "max",
        n_initial: int = 10,
        gamma: float = 0.25,
        n_candidates: int = 24,
        seed=None,
    ):
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        self.param_space = dict(param_space)
        # grid_search axes degrade to categorical choices under TPE.
        for k, v in self.param_space.items():
            if isinstance(v, dict) and set(v.keys()) == {"grid_search"}:
                self.param_space[k] = Choice(v["grid_search"])
        self.metric = metric
        self.mode = mode
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self._configs: dict[str, dict] = {}  # trial_id → config
        self._history: list[tuple[dict, float]] = []  # (config, score)

    # -- observation model helpers ------------------------------------
    def _numeric_span(self, dom) -> tuple[float, float, bool]:
        """(low, high, log_scale) of a numeric domain."""
        import math

        if isinstance(dom, Uniform):
            return dom.low, dom.high, False
        if isinstance(dom, LogUniform):
            return math.exp(dom.lo), math.exp(dom.hi), True
        if isinstance(dom, RandInt):
            return float(dom.low), float(dom.high - 1), False
        raise TypeError(dom)

    def _kde_logpdf(self, dom, values: list, x: float) -> float:
        """Parzen estimate: mixture of gaussians at each observation."""
        import math

        low, high, logscale = self._numeric_span(dom)
        if logscale:
            low, high = math.log(low), math.log(high)
            x = math.log(max(x, 1e-300))
            values = [math.log(max(v, 1e-300)) for v in values]
        sigma = max((high - low), 1e-12) / max(math.sqrt(len(values)), 1.0)
        acc = 0.0
        for v in values:
            z = (x - v) / sigma
            acc += math.exp(-0.5 * z * z)
        return math.log(max(acc / (len(values) * sigma), 1e-300))

    def _sample_from(self, dom, values: list):
        """Draw near a random good observation (Parzen sampling)."""
        import math

        low, high, logscale = self._numeric_span(dom)
        if logscale:
            low, high = math.log(low), math.log(high)
            values = [math.log(max(v, 1e-300)) for v in values]
        sigma = max((high - low), 1e-12) / max(math.sqrt(len(values)), 1.0)
        center = self.rng.choice(values)
        x = min(max(self.rng.gauss(center, sigma), low), high)
        if logscale:
            x = math.exp(x)
        if isinstance(dom, RandInt):
            return int(round(min(max(x, dom.low), dom.high - 1)))
        return x

    # -- Searcher interface -------------------------------------------
    def suggest(self, trial_id: str) -> dict | None:
        import math

        tunable = {
            k: v for k, v in self.param_space.items()
            if isinstance(v, Domain)
        }
        config = {
            k: v for k, v in self.param_space.items()
            if not isinstance(v, Domain)
        }
        if len(self._history) < self.n_initial or not tunable:
            for k, dom in tunable.items():
                config[k] = dom.sample(self.rng)
            self._configs[trial_id] = config
            return config

        ranked = sorted(self._history, key=lambda t: -t[1])
        n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
        good = [c for c, _s in ranked[:n_good]]
        bad = [c for c, _s in ranked[n_good:]] or good

        best_cfg, best_score = None, None
        for _ in range(self.n_candidates):
            cand = dict(config)
            score = 0.0
            for k, dom in tunable.items():
                if isinstance(dom, Choice):
                    counts = {c: 1.0 for c in map(repr, dom.categories)}
                    for g in good:
                        counts[repr(g[k])] = counts.get(repr(g[k]), 1.0) + 1
                    total = sum(counts.values())
                    r = self.rng.uniform(0, total)
                    acc = 0.0
                    pick = dom.categories[-1]
                    for cat in dom.categories:
                        acc += counts[repr(cat)]
                        if r <= acc:
                            pick = cat
                            break
                    cand[k] = pick
                    bad_counts = {c: 1.0 for c in map(repr, dom.categories)}
                    for b in bad:
                        bad_counts[repr(b[k])] = (
                            bad_counts.get(repr(b[k]), 1.0) + 1
                        )
                    score += math.log(
                        counts[repr(pick)] / sum(counts.values())
                    ) - math.log(
                        bad_counts[repr(pick)] / sum(bad_counts.values())
                    )
                else:
                    x = self._sample_from(dom, [g[k] for g in good])
                    cand[k] = x
                    score += self._kde_logpdf(
                        dom, [g[k] for g in good], x
                    ) - self._kde_logpdf(dom, [b[k] for b in bad], x)
            if best_score is None or score > best_score:
                best_cfg, best_score = cand, score
        self._configs[trial_id] = best_cfg
        return best_cfg

    def on_trial_complete(self, trial_id: str, result: dict | None):
        config = self._configs.pop(trial_id, None)
        if config is None or result is None or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._history.append((config, score))


class ConcurrencyLimiter(Searcher):
    """Cap in-flight suggestions (reference:
    tune/search/concurrency_limiter.py). Returns DEFER while the cap is
    reached so the controller retries later instead of finishing."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set[str] = set()

    def suggest(self, trial_id: str):
        if len(self._live) >= self.max_concurrent:
            return DEFER
        config = self.searcher.suggest(trial_id)
        if config is not None and config is not DEFER:
            self._live.add(trial_id)
        return config

    def on_trial_complete(self, trial_id: str, result: dict | None):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result)


class Repeater(Searcher):
    """Repeat each suggested config N times; once the group completes,
    report ONE result to the wrapped searcher, with ``metric`` (when
    given) averaged across repeats (reference: tune/search/repeater.py —
    de-noises stochastic objectives)."""

    def __init__(self, searcher: Searcher, repeat: int, metric: str | None = None):
        self.searcher = searcher
        self.repeat = max(1, repeat)
        self.metric = metric
        self._pending: list[tuple[str, dict]] = []  # queued repeats
        self._group_of: dict[str, str] = {}  # trial_id → group id
        self._results: dict[str, list] = {}  # group id → results

    def suggest(self, trial_id: str):
        if self._pending:
            group, config = self._pending.pop(0)
            self._group_of[trial_id] = group
            return dict(config)
        config = self.searcher.suggest(trial_id)
        if config is None or config is DEFER:
            return config
        group = trial_id
        self._group_of[trial_id] = group
        self._results[group] = []
        for _ in range(self.repeat - 1):
            self._pending.append((group, config))
        return config

    def on_trial_complete(self, trial_id: str, result: dict | None):
        group = self._group_of.pop(trial_id, None)
        if group is None:
            return
        bucket = self._results.get(group)
        if bucket is None:
            return
        bucket.append(result)
        if len(bucket) < self.repeat:
            return
        del self._results[group]
        ok = [r for r in bucket if r]
        if not ok:
            self.searcher.on_trial_complete(group, None)
            return
        merged = dict(ok[-1])
        if self.metric:
            # Only the declared metric is averaged; every other field
            # (iteration counters, timestamps) passes through untouched.
            vals = [r[self.metric] for r in ok if self.metric in r]
            if vals:
                merged[self.metric] = sum(vals) / len(vals)
        self.searcher.on_trial_complete(group, merged)
