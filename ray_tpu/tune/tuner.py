"""Tuner + controller event loop.

Mirrors the reference's Tune v2 control plane (reference:
python/ray/tune/execution/tune_controller.py:68 — an event loop over
trial actors that starts trials up to the resource cap, consumes
results, and applies scheduler decisions; tuner.py Tuner.fit →
ResultGrid). PBT exploitation uses the class-API save/restore path.
"""

from __future__ import annotations

import inspect
import logging
import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable

import ray_tpu
from ray_tpu import exceptions as _exc
from ray_tpu.tune import schedulers as S
from ray_tpu.tune.search import DEFER, BasicVariantGenerator, Searcher

logger = logging.getLogger("ray_tpu.tune")

# Typed trial-failure classes (reference: the v2 controller's
# failure-policy split, python/ray/train/v2/_internal/execution/
# failure_handling) — each gets a different retry policy:
# - "preempted": the node under the trial was reclaimed. Never the
#   trial's fault; restart unconditionally from its last checkpoint.
# - "infra": actor/object plumbing died (worker crash, object loss,
#   RPC timeout). Retry up to TUNE_INFRA_RETRIES, then give up.
# - "trial": the trainable itself raised. A user bug — retrying
#   re-raises it, so fail fast.
PREEMPTED = "preempted"
INFRA = "infra"
TRIAL = "trial"

_INFRA_TYPES = (
    _exc.WorkerDiedError,
    _exc.ActorDiedError,
    _exc.ObjectLostError,
    _exc.GetTimeoutError,
)


def classify_failure(err: BaseException | str) -> str:
    """Classify a trial failure as PREEMPTED, INFRA, or TRIAL.

    Walks the cause chain (RayTaskError wraps the user exception in
    ``.cause``) so a PreemptedError surfacing through task-error
    plumbing is still recognized as a preemption, not an infra flake.
    """
    seen: set[int] = set()
    cur: BaseException | None = (
        err if isinstance(err, BaseException) else None
    )
    text = str(err)
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        if isinstance(cur, _exc.PreemptedError):
            return PREEMPTED
        if isinstance(cur, _INFRA_TYPES):
            return INFRA
        cur = getattr(cur, "cause", None) or getattr(
            cur, "__cause__", None
        )
    if "PreemptedError" in text:
        return PREEMPTED
    if any(t.__name__ in text for t in _INFRA_TYPES):
        return INFRA
    return TRIAL
from ray_tpu.tune.trial import (
    ERROR,
    PENDING,
    RUNNING,
    TERMINATED,
    Trainable,
    Trial,
    TrialActor,
)


@dataclass
class TuneConfig:
    num_samples: int = 1
    max_concurrent_trials: int = 4
    metric: str | None = None
    mode: str = "max"
    scheduler: S.TrialScheduler | None = None
    search_alg: Searcher | None = None
    seed: Any = None
    max_iterations: int | None = None  # class-API step cap


@dataclass
class RunConfig:
    name: str = "tune_run"
    storage_path: str = "/tmp/ray_tpu_results"
    # tune.Callback instances (loggers / experiment trackers — see
    # tune/callbacks.py); hooks fire per trial start/result/complete.
    callbacks: tuple = ()


@dataclass
class TrialResult:
    config: dict
    metrics: dict
    checkpoint: str | None
    path: str
    error: str | None = None


class ResultGrid:
    def __init__(self, results: list[TrialResult], metric=None, mode="max"):
        self._results = results
        self._metric, self._mode = metric, mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i):
        return self._results[i]

    @property
    def errors(self):
        return [r for r in self._results if r.error]

    def get_best_result(self, metric: str | None = None, mode: str | None = None):
        metric = metric or self._metric
        mode = mode or self._mode
        ok = [r for r in self._results if not r.error and metric in r.metrics]
        if not ok:
            raise ValueError("no successful trial reported " + str(metric))
        return (max if mode == "max" else min)(
            ok, key=lambda r: r.metrics[metric]
        )

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for r in self._results:
            row = dict(r.metrics)
            row.update({f"config/{k}": v for k, v in r.config.items()})
            rows.append(row)
        return pd.DataFrame(rows)


class Tuner:
    def __init__(self, trainable, *, param_space: dict | None = None,
                 tune_config: TuneConfig | None = None,
                 run_config: RunConfig | None = None):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()

    def fit(self) -> ResultGrid:
        cfg = self.tune_config
        if cfg.search_alg is not None:
            # num_samples bounds TOTAL trials for pluggable searchers
            # (BasicVariant bakes it into its own queue).
            searcher = _CapSamples(cfg.search_alg, cfg.num_samples)
        else:
            searcher = BasicVariantGenerator(
                self.param_space, num_samples=cfg.num_samples, seed=cfg.seed
            )
        scheduler = cfg.scheduler or S.FIFOScheduler()
        exp_dir = os.path.join(self.run_config.storage_path, self.run_config.name)
        os.makedirs(exp_dir, exist_ok=True)
        is_class = inspect.isclass(self.trainable) and issubclass(
            self.trainable, Trainable
        )
        callbacks = list(self.run_config.callbacks)
        for cb in callbacks:
            # Loggers default their output into THIS experiment's dir;
            # re-point auto-filled ones on reuse across fits (a sticky
            # exp_dir would append run B's rows into run A's files).
            if getattr(cb, "exp_dir", "unset") is None or getattr(
                cb, "_auto_exp_dir", False
            ):
                cb.exp_dir = exp_dir
                cb._auto_exp_dir = True
        controller = _TuneController(
            self.trainable, is_class, searcher, scheduler, cfg, exp_dir,
            callbacks=callbacks,
        )
        results = controller.run()
        return ResultGrid(results, metric=cfg.metric, mode=cfg.mode)


class _CapSamples(Searcher):
    """Bound a pluggable searcher to num_samples total suggestions."""

    def __init__(self, searcher: Searcher, num_samples: int):
        self.searcher = searcher
        self.remaining = num_samples

    def suggest(self, trial_id: str):
        if self.remaining <= 0:
            return None
        config = self.searcher.suggest(trial_id)
        if config is not None and config is not DEFER:
            self.remaining -= 1
        return config

    def on_trial_complete(self, trial_id: str, result: dict | None):
        self.searcher.on_trial_complete(trial_id, result)


class _TuneController:
    """(reference: TuneController tune_controller.py:68 — state machine
    stepping trials and consuming results.)"""

    def __init__(self, trainable, is_class, searcher, scheduler, cfg,
                 exp_dir, callbacks=()):
        self.trainable = trainable
        self.is_class = is_class
        self.searcher = searcher
        self.scheduler = scheduler
        self.cfg = cfg
        self.exp_dir = exp_dir
        self.callbacks = list(callbacks)
        self._cb_warned: set = set()
        self.trials: list[Trial] = []
        self._next_id = 0
        self._exhausted = False

    def _cb(self, hook: str, *args) -> None:
        """Fire a callback hook; a logger bug degrades logging, not the
        run — but it is WARNED (once per callback+hook), because a
        silently-swallowed signature error would otherwise produce an
        empty log dir with zero diagnostics."""
        for cb in self.callbacks:
            try:
                getattr(cb, hook)(*args)
            except Exception as e:  # noqa: BLE001
                key = (id(cb), hook)
                if key not in self._cb_warned:
                    self._cb_warned.add(key)
                    logger.warning(
                        "callback %s.%s failed (suppressed): %r",
                        type(cb).__name__, hook, e,
                    )

    def _new_trial(self) -> Trial | None:
        trial_id = f"t{self._next_id:04d}"
        config = self.searcher.suggest(trial_id)
        if config is None:
            self._exhausted = True
            return None
        if config is DEFER:  # not now (concurrency-limited) — retry later
            return None
        trial = Trial(
            trial_id, config,
            os.path.join(self.exp_dir, f"trial_{self._next_id:04d}"),
        )
        self._next_id += 1
        self.trials.append(trial)
        return trial

    def _start(self, trial: Trial):
        trial.actor = TrialActor.remote(trial.local_dir)
        trial.is_class_api = self.is_class
        if self.is_class:
            ray_tpu.get(trial.actor.setup_class.remote(
                self.trainable, trial.config, trial.checkpoint))
        else:
            ray_tpu.get(trial.actor.start_fn.remote(
                self.trainable, trial.config, trial.checkpoint))
        trial.status = RUNNING
        self._cb("on_trial_start", trial.trial_id, trial.config)

    def _finish(self, trial: Trial, status: str, error: str | None = None):
        trial.status = status
        trial.error = error
        self._cb(
            "on_trial_complete", trial.trial_id,
            trial.last_result if error is None else None, error,
        )
        # Feed the searcher so adaptive algorithms learn from outcomes
        # (reference: SearchAlgorithm.on_trial_complete, tune/search/).
        try:
            self.searcher.on_trial_complete(
                trial.trial_id, trial.last_result if error is None else None
            )
        except Exception:  # noqa: BLE001 - searcher bugs must not kill the run
            logger.warning(
                "searcher.on_trial_complete failed for %s; later "
                "suggestions may ignore this result", trial.trial_id,
                exc_info=True,
            )
        if trial.actor is not None:
            try:
                if trial.is_class_api:
                    ray_tpu.get(trial.actor.shutdown.remote())
                ray_tpu.kill(trial.actor)
            # tpulint: allow(broad-except reason=the trial actor is expected to be dead on the error path; a second kill has nothing to report)
            except Exception:  # noqa: BLE001 - actor may already be dead
                pass
            trial.actor = None

    def _handle_trial_failure(self, trial: Trial, err: Exception):
        """Apply the typed failure policy (see classify_failure)."""
        from ray_tpu._private import config as _config

        kind = classify_failure(err)
        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            # tpulint: allow(broad-except reason=the failed trial's actor is usually already dead; the kill is best-effort cleanup)
            except Exception:  # noqa: BLE001
                pass
            trial.actor = None
        if kind == PREEMPTED:
            logger.warning(
                "trial %s preempted (attempt %d); restarting from %s",
                trial.trial_id, trial.infra_retries + 1,
                trial.checkpoint or "scratch",
            )
            self._start(trial)
            return
        if kind == INFRA:
            budget = _config.get("TUNE_INFRA_RETRIES")
            if trial.infra_retries < budget:
                trial.infra_retries += 1
                logger.warning(
                    "trial %s hit infra failure %s (retry %d/%d): %s",
                    trial.trial_id, type(err).__name__,
                    trial.infra_retries, budget, err,
                )
                self._start(trial)
                return
            logger.error(
                "trial %s exhausted %d infra retries; failing: %s",
                trial.trial_id, budget, err,
            )
            self._finish(trial, ERROR, error=f"[infra] {err}")
            return
        # Trial-code bug: retrying would just re-raise it.
        logger.error(
            "trial %s failed in trial code; failing fast: %s",
            trial.trial_id, err,
        )
        self._finish(trial, ERROR, error=f"[trial] {err}")

    def _running(self):
        return [t for t in self.trials if t.status == RUNNING]

    def _run_inner(self) -> list:
        cap = max(1, self.cfg.max_concurrent_trials)
        while True:
            # Fill free slots.
            while not self._exhausted and len(self._running()) < cap:
                t = self._new_trial()
                if t is None:
                    break
                self._start(t)
            running = self._running()
            if not running:
                if self._exhausted:
                    break
                time.sleep(0.05)  # deferred suggestions: retry shortly
                continue
            if self.is_class:
                self._step_class_trials(running)
            else:
                self._poll_fn_trials(running)
        results = [
            TrialResult(
                config=t.config, metrics=t.last_result,
                checkpoint=t.checkpoint, path=t.local_dir, error=t.error,
            )
            for t in self.trials
        ]
        return results

    def run(self) -> list:
        try:
            return self._run_inner()
        finally:
            # Teardown hooks must fire even when a trial actor dies on
            # an unguarded path — otherwise log files stay open and
            # tracker runs are left dangling.
            self._cb(
                "on_experiment_end",
                [
                    TrialResult(
                        config=t.config, metrics=t.last_result,
                        checkpoint=t.checkpoint, path=t.local_dir,
                        error=t.error,
                    )
                    for t in self.trials
                ],
            )

    # ------------------------------------------------------- class API
    def _step_class_trials(self, running: list):
        # One synchronous step per running trial per tick; all results are
        # recorded before any decision so rung/quantile comparisons see
        # every peer at the same milestone (schedulers' two-phase hook).
        step_refs = [(t, t.actor.train_step.remote()) for t in running]
        batch = []
        for t, ref in step_refs:
            try:
                metrics = ray_tpu.get(ref)
            # tpulint: allow(broad-except reason=the failure is classified and either retried or recorded as the trial's terminal error)
            except Exception as e:  # noqa: BLE001
                self._handle_trial_failure(t, e)
                continue
            t.iteration = metrics.get("training_iteration", t.iteration + 1)
            t.results.append(metrics)
            t.last_result = metrics
            self._cb("on_trial_result", t.trial_id, t.config, metrics)
            batch.append((t, metrics))
        decisions = self.scheduler.on_batch(batch, self.trials)
        max_it = self.cfg.max_iterations
        for t, metrics in batch:
            decision = decisions.get(t.trial_id, S.CONTINUE)
            if decision == S.STOP or (max_it and t.iteration >= max_it):
                t.checkpoint = ray_tpu.get(t.actor.save.remote())
                self._finish(t, TERMINATED)
            elif decision == S.EXPLOIT:
                self._exploit(t)

    def _exploit(self, trial: Trial):
        """PBT: clone a top trial's checkpoint + perturbed config
        (reference: pbt.py _exploit)."""
        source = self.scheduler.choose_exploit_source(trial, self._running())
        if source is None or source.actor is None:
            return
        ckpt = ray_tpu.get(source.actor.save.remote())
        new_config = self.scheduler.perturb(source.config)
        trial.config = new_config
        ray_tpu.get(trial.actor.restore.remote(
            ckpt, config=new_config, iteration=source.iteration))
        trial.iteration = source.iteration

    # ---------------------------------------------------- function API
    def _poll_fn_trials(self, running: list):
        time.sleep(0.05)
        polls = [(t, t.actor.poll.remote()) for t in running]
        for t, ref in polls:
            try:
                out = ray_tpu.get(ref)
            # tpulint: allow(broad-except reason=the failure is classified and either retried or recorded as the trial's terminal error)
            except Exception as e:  # noqa: BLE001
                self._handle_trial_failure(t, e)
                continue
            stopped = False
            for entry in out["reports"]:
                metrics = entry["metrics"]
                t.iteration = metrics.get("training_iteration", t.iteration + 1)
                metrics.setdefault("training_iteration", t.iteration)
                t.results.append(metrics)
                t.last_result = metrics
                self._cb(
                    "on_trial_result", t.trial_id, t.config, metrics
                )
                if "checkpoint" in entry:
                    t.checkpoint = entry["checkpoint"]
                decision = self.scheduler.on_result(t, metrics, self.trials)
                if decision == S.STOP:
                    ray_tpu.get(t.actor.stop_fn.remote())
                    self._finish(t, TERMINATED)
                    stopped = True
                    break
            if stopped:
                continue
            if out["done"]:
                t.checkpoint = out["checkpoint"] or t.checkpoint
                if out["error"]:
                    # The fn session reports failures as strings;
                    # classify by name so a preemption surfacing
                    # through the session still restarts the trial.
                    kind = classify_failure(out["error"])
                    if kind == PREEMPTED:
                        logger.warning(
                            "trial %s preempted (reported); restarting "
                            "from %s", t.trial_id,
                            t.checkpoint or "scratch",
                        )
                        if t.actor is not None:
                            try:
                                ray_tpu.kill(t.actor)
                            # tpulint: allow(broad-except reason=the preempted trial's actor is usually already dead; the kill is best-effort cleanup)
                            except Exception:  # noqa: BLE001
                                pass
                            t.actor = None
                        self._start(t)
                    else:
                        self._finish(
                            t, ERROR, error=f"[{kind}] {out['error']}"
                        )
                else:
                    self._finish(t, TERMINATED)
