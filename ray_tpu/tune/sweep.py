"""Sweep engine: gang-scheduled multi-trial orchestration.

Where ``tune.Tuner`` drives lightweight single-actor trials, ``Sweep``
drives trials that are each a GANG of TrainWorkers (a ``JaxTrainer``
fit), and wires them into the cluster's control plane:

- **Gang scheduling with admission** — a trial launches only when
  ``train.admission.admit_gang`` says yes twice over: the memory
  planner prices the config onto a chip (fits + headroom), and the
  head's slice/node tables show enough healthy chips free. Admitted
  gangs pack onto idle chips concurrently; rejected ones wait in the
  admission queue instead of thrashing the placement layer.
- **Ledger-driven early stopping** — the scheduler (``LedgerASHA``)
  reads per-trial loss/goodput from the head's existing ``train_stats``
  fold (each trial is a train job named ``<sweep>/<trial>``); there is
  NO sweep-private reporting path. Stops at rung boundaries kill the
  gang via ``JaxTrainer.request_stop``.
- **Checkpoint-forked PBT** — an exploit stops the loser, forks the
  winner's newest complete checkpoint manifest into the loser's run
  (``checkpoint.fork`` — a zero-byte content-addressed copy), and
  relaunches the loser with perturbed hyperparameters restoring from
  the forked manifest.
- **Preemption-tolerant migration** — a gang on a draining node takes
  the emergency-checkpoint unwind (train/session.py), and the
  trainer's own retry loop re-places it on healthy chips; the sweep
  counts the migration and verifies ≤1 step of ledger loss. Trial
  state transitions are journaled to the head's ``sweeps`` table, so
  a head SIGKILL mid-sweep replays them.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import ray_tpu
from ray_tpu.tune.schedulers import LedgerASHA, LedgerPBT, STOP
from ray_tpu.tune.search import BasicVariantGenerator

logger = logging.getLogger("ray_tpu.tune")

PENDING = "PENDING"
RUNNING = "RUNNING"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclass
class SweepConfig:
    num_samples: int = 8
    metric: str = "loss"        # ledger field: "loss" or "goodput"
    mode: str = "min"
    workers_per_trial: int = 1
    chips_per_worker: float = 0.0   # >0: each worker leases TPU chips
    # Extra per-worker resources (e.g. {"SLICE": 1.0}) merged into the
    # gang's bundles on top of the chip lease.
    resources_per_worker: dict | None = None
    scheduler: LedgerASHA | None = None
    pbt: LedgerPBT | None = None
    max_steps: int | None = None    # ledger-steps cap per trial
    max_concurrent: int = 0         # 0 → TUNE_MAX_CONCURRENT knob
    plan_kwargs: dict | None = None  # admission memory pricing
    max_failures: int = 4           # per-gang trainer retry budget
    poll_s: float | None = None     # 0 valid; None → TUNE_POLL_S knob
    seed: Any = None


@dataclass
class SweepTrialResult:
    trial_id: str
    config: dict
    state: str
    ledger: dict = field(default_factory=dict)
    checkpoint: str | None = None
    error: str | None = None
    attempts: int = 0
    forked_from: str | None = None


class SweepResult:
    def __init__(self, sweep_id: str, trials: list[SweepTrialResult],
                 metric: str, mode: str, stats: dict):
        self.sweep_id = sweep_id
        self.trials = trials
        self._metric, self._mode = metric, mode
        # makespan / utilization samples / fork+preemption counters
        self.stats = stats

    def __len__(self):
        return len(self.trials)

    def __iter__(self):
        return iter(self.trials)

    def best(self) -> SweepTrialResult:
        ok = [
            t for t in self.trials
            if t.state != ERROR and t.ledger.get(self._metric) is not None
        ]
        if not ok:
            raise ValueError(f"no trial reported ledger {self._metric!r}")
        return (max if self._mode == "max" else min)(
            ok, key=lambda t: t.ledger[self._metric]
        )


class _SweepTrial:
    __slots__ = (
        "trial_id", "config", "job", "state", "trainer", "thread",
        "result", "error", "stop_reason", "attempts_seen",
        "forked_from", "relaunch", "started_ts", "ended_ts",
    )

    def __init__(self, trial_id: str, config: dict, job: str):
        self.trial_id = trial_id
        self.config = config
        self.job = job
        self.state = PENDING
        self.trainer = None
        self.thread: threading.Thread | None = None
        self.result = None
        self.error: str | None = None
        self.stop_reason: str | None = None
        self.attempts_seen = 0
        self.forked_from: str | None = None
        self.relaunch = False
        self.started_ts: float | None = None
        self.ended_ts: float | None = None


class Sweep:
    """Run ``num_samples`` gang trials of ``train_loop`` over
    ``param_space`` (grid_search / Domain values — the same search
    space language as ``tune.Tuner``)."""

    def __init__(
        self,
        train_loop: Callable,
        param_space: dict | None = None,
        *,
        sweep_id: str | None = None,
        storage_path: str = "/tmp/ray_tpu_sweeps",
        config: SweepConfig | None = None,
    ):
        self.train_loop = train_loop
        self.param_space = param_space or {}
        self.cfg = config or SweepConfig()
        self.sweep_id = sweep_id or f"sweep-{int(time.time()) % 100000}"
        self.storage_path = storage_path
        self.trials: list[_SweepTrial] = []
        self.forks = 0
        self.preemptions = 0
        # (ts, free_chips, total_chips) samples for idle accounting
        self.utilization: list[tuple[float, float, float]] = []

    # ------------------------------------------------------- head I/O
    def _head_call(self, method: str, **kw):
        rt = ray_tpu.api._runtime
        return rt.run(rt.core.head.call(method, **kw))

    def _journal_sweep(self, **fields) -> None:
        try:
            self._head_call(
                "sweep_put", sweep_id=self.sweep_id, fields=fields
            )
        except Exception:  # noqa: BLE001 - journaling must not stop trials
            logger.debug("sweep_put failed", exc_info=True)

    def _journal_trial(self, t: _SweepTrial, **extra) -> None:
        fields = {
            "state": t.state,
            "config": dict(t.config),
            "job": t.job,
            "attempts": t.attempts_seen,
            "forked_from": t.forked_from,
            "stop_reason": t.stop_reason,
            "ts": time.time(),
            **extra,
        }
        try:
            self._head_call(
                "sweep_trial",
                sweep_id=self.sweep_id,
                trial_id=t.trial_id,
                fields=fields,
            )
        except Exception:  # noqa: BLE001 - journaling must not stop trials
            logger.debug("sweep_trial failed", exc_info=True)

    def _ledger_rows(self) -> dict[str, dict]:
        """trial_id → public ledger row, via the existing train_stats
        fold (trial jobs are train jobs named <sweep>/<trial>)."""
        try:
            jobs = self._head_call("train_stats").get("jobs", {})
        except Exception:  # noqa: BLE001 - head busy: empty poll
            logger.debug("train_stats poll failed", exc_info=True)
            return {}
        out = {}
        for t in self.trials:
            row = jobs.get(t.job)
            if row is not None:
                out[t.trial_id] = row
        return out

    # ------------------------------------------------------ lifecycle
    def _make_trainer(self, t: _SweepTrial):
        from ray_tpu import train

        rpw = dict(self.cfg.resources_per_worker or {})
        if self.cfg.chips_per_worker > 0:
            rpw.setdefault("TPU", self.cfg.chips_per_worker)
        scaling = train.ScalingConfig(
            num_workers=self.cfg.workers_per_trial,
            resources_per_worker=rpw,
        )
        run_config = train.RunConfig(
            name=t.job,
            storage_path=self.storage_path,
            failure_config=train.FailureConfig(
                max_failures=self.cfg.max_failures
            ),
            sweep_id=self.sweep_id,
            trial_id=t.trial_id,
            # Fresh trials discover nothing; PBT-relaunched trials pick
            # up the manifest forked into their run name.
            resume_from_checkpoint="auto",
        )
        return train.JaxTrainer(
            self.train_loop,
            train_loop_config=dict(t.config),
            scaling_config=scaling,
            run_config=run_config,
        )

    def _launch(self, t: _SweepTrial) -> None:
        t.trainer = self._make_trainer(t)
        t.state = RUNNING
        t.started_ts = t.started_ts or time.time()
        self._journal_trial(t)

        def body():
            try:
                t.result = t.trainer.fit()
                if t.result.error is not None:
                    t.error = (
                        f"{type(t.result.error).__name__}: "
                        f"{t.result.error}"
                    )
            except Exception as e:  # noqa: BLE001 - thread boundary
                logger.debug("trial %s fit raised", t.trial_id,
                             exc_info=True)
                t.error = f"{type(e).__name__}: {e}"

        t.thread = threading.Thread(
            target=body, name=f"sweep-{t.trial_id}", daemon=True
        )
        t.thread.start()

    def _request_stop(self, t: _SweepTrial, reason: str) -> None:
        t.stop_reason = reason
        if t.trainer is not None:
            t.trainer.request_stop()

    def _reap(self, t: _SweepTrial) -> None:
        """Fold a finished thread into the trial's terminal state (or
        queue a PBT relaunch)."""
        t.thread = None
        if t.relaunch:
            t.relaunch = False
            t.state = PENDING
            t.trainer = None
            t.error = None
            t.stop_reason = None
            return
        t.ended_ts = time.time()
        if t.stop_reason is not None or t.error is None:
            t.state = TERMINATED
        else:
            t.state = ERROR
        self._journal_trial(t)

    def _admit_and_launch(self, pending: list[_SweepTrial]) -> None:
        from ray_tpu._private import config as _config
        from ray_tpu.train import admission

        cap = self.cfg.max_concurrent or _config.get("TUNE_MAX_CONCURRENT")
        running = sum(1 for t in self.trials if t.state == RUNNING)
        try:
            status = self._head_call("cluster_status")
        except Exception:  # noqa: BLE001 - head busy: admit nothing
            logger.debug("cluster_status poll failed", exc_info=True)
            return
        free, total = admission.cluster_chips(status)
        self.utilization.append((time.time(), free, total))
        for t in pending:
            if cap and running >= cap:
                break
            ticket = admission.admit_gang(
                self.cfg.workers_per_trial,
                self.cfg.chips_per_worker,
                plan_kwargs=self.cfg.plan_kwargs,
                status=status,
            )
            if not ticket:
                if ticket.plan is not None and not ticket.plan.fits:
                    # A config the planner rejects outright never fits
                    # any chip — waiting won't help.
                    t.state = ERROR
                    t.error = f"admission: {ticket.reason}"
                    self._journal_trial(t)
                    continue
                logger.debug(
                    "trial %s waiting for admission: %s",
                    t.trial_id, ticket.reason,
                )
                break  # FIFO admission: don't starve the head of queue
            # Account the gang's chips against this tick's snapshot so
            # several pending trials don't all admit against the same
            # free chips.
            nodes = status.get("nodes") or {}
            kind = "TPU" if any(
                (n.get("resources") or {}).get("TPU")
                for n in nodes.values()
            ) else "CPU"
            need = self.cfg.workers_per_trial * self.cfg.chips_per_worker
            for n in nodes.values():
                avail = n.get("available") or {}
                take = min(need, float(avail.get(kind, 0.0)))
                if take > 0:
                    avail[kind] = float(avail.get(kind, 0.0)) - take
                    need -= take
                if need <= 0:
                    break
            self._launch(t)
            running += 1

    # ---------------------------------------------------------- steps
    def _apply_scheduler(self, rows: dict[str, dict]) -> None:
        sched = self.cfg.scheduler
        by_id = {t.trial_id: t for t in self.trials}
        for tid, row in rows.items():
            t = by_id[tid]
            if t.state != RUNNING:
                continue
            steps = int(row.get("steps") or 0)
            # Migration accounting: each extra ledger attempt is a gang
            # that died (preemption / node loss) and re-admitted.
            attempts = int(row.get("attempts") or 0)
            if attempts > max(1, t.attempts_seen):
                self.preemptions += attempts - max(1, t.attempts_seen)
                t.attempts_seen = attempts
                self._journal_sweep(preemptions=self.preemptions)
                self._journal_trial(t)
            elif attempts > 0:
                t.attempts_seen = attempts
            if self.cfg.max_steps and steps >= self.cfg.max_steps:
                self._request_stop(t, "max_steps")
                continue
            if sched is None:
                continue
            value = row.get(self.cfg.metric)
            if sched.decide(tid, steps, value) == STOP:
                logger.info(
                    "sweep %s: stopping trial %s at rung (steps=%d, "
                    "%s=%s)", self.sweep_id, tid, steps,
                    self.cfg.metric, value,
                )
                self._request_stop(t, "rung")

    def _apply_pbt(self, rows: dict[str, dict]) -> None:
        pbt = self.cfg.pbt
        if pbt is None:
            return
        by_id = {t.trial_id: t for t in self.trials}
        pbt_rows = {
            tid: (int(r.get("steps") or 0), r.get(self.cfg.metric))
            for tid, r in rows.items()
            if by_id[tid].state == RUNNING
        }
        for loser_id, winner_id in pbt.exploit_pairs(pbt_rows):
            loser, winner = by_id[loser_id], by_id[winner_id]
            if loser.state != RUNNING or winner.state != RUNNING:
                continue
            logger.info(
                "sweep %s: PBT exploit — %s forks %s's checkpoint",
                self.sweep_id, loser_id, winner_id,
            )
            loser.relaunch = True
            loser.forked_from = winner_id
            loser.config = pbt.perturb(winner.config)
            self._request_stop(loser, "exploit")

    def _maybe_fork(self, t: _SweepTrial) -> None:
        """Complete a queued PBT exploit after the loser's gang is
        down: fork the winner's newest complete manifest into the
        loser's run (zero bulk bytes) so the relaunch restores it."""
        if t.forked_from is None or t.state != PENDING:
            return
        winner = next(
            (w for w in self.trials if w.trial_id == t.forked_from), None
        )
        if winner is None:
            return
        from ray_tpu import checkpoint as ckpt

        try:
            reply = ckpt.fork(winner.job, t.job)
        except ValueError as e:
            # No complete checkpoint yet: relaunch fresh with the
            # perturbed config — the exploit still moved the
            # hyperparameters.
            logger.info("PBT fork skipped for %s: %s", t.trial_id, e)
            return
        self.forks += 1
        assert reply["new_bytes"] == 0, (
            "content-addressed fork moved bytes: " + repr(reply)
        )
        self._journal_sweep(forks=self.forks)
        self._journal_trial(t, fork_step=reply["step"])

    # ------------------------------------------------------------ run
    def run(self) -> SweepResult:
        from ray_tpu._private import config as _config

        cfg = self.cfg
        poll_s = (
            cfg.poll_s if cfg.poll_s is not None
            else _config.get("TUNE_POLL_S")
        )
        searcher = BasicVariantGenerator(
            self.param_space, num_samples=cfg.num_samples, seed=cfg.seed
        )
        i = 0
        while True:
            trial_id = f"t{i:04d}"
            config = searcher.suggest(trial_id)
            if config is None:
                break
            self.trials.append(
                _SweepTrial(
                    trial_id, config, f"{self.sweep_id}/{trial_id}"
                )
            )
            i += 1
        t0 = time.time()
        self._journal_sweep(
            state=RUNNING,
            num_samples=len(self.trials),
            metric=cfg.metric,
            mode=cfg.mode,
            scheduler=type(cfg.scheduler).__name__
            if cfg.scheduler else None,
            pbt=cfg.pbt is not None,
            workers_per_trial=cfg.workers_per_trial,
            forks=0,
            preemptions=0,
            started_ts=t0,
        )
        for t in self.trials:
            self._journal_trial(t)
        while True:
            # Reap finished gangs (and queue PBT relaunches).
            for t in self.trials:
                if t.thread is not None and not t.thread.is_alive():
                    self._reap(t)
            pending = [t for t in self.trials if t.state == PENDING]
            for t in pending:
                self._maybe_fork(t)
            self._admit_and_launch(pending)
            live = [t for t in self.trials if t.state == RUNNING]
            if not live and not pending:
                break
            rows = self._ledger_rows()
            self._apply_scheduler(rows)
            self._apply_pbt(rows)
            time.sleep(poll_s)
        makespan = time.time() - t0
        self._journal_sweep(
            state="FINISHED", makespan_s=makespan,
            forks=self.forks, preemptions=self.preemptions,
        )
        rows = self._ledger_rows()
        results = [
            SweepTrialResult(
                trial_id=t.trial_id,
                config=dict(t.config),
                state=t.state,
                ledger=rows.get(t.trial_id, {}),
                checkpoint=(
                    t.result.checkpoint if t.result is not None else None
                ),
                error=t.error,
                attempts=t.attempts_seen,
                forked_from=t.forked_from,
            )
            for t in self.trials
        ]
        return SweepResult(
            self.sweep_id, results, cfg.metric, cfg.mode,
            stats={
                "makespan_s": makespan,
                "forks": self.forks,
                "preemptions": self.preemptions,
                "utilization": list(self.utilization),
                "chip_idle_fraction": self.chip_idle_fraction(),
            },
        )

    def chip_idle_fraction(self) -> float | None:
        """Time-weighted mean of free/total chips over the sweep (the
        bench's packing-efficiency number). None without samples."""
        samples = [
            (ts, free, total)
            for ts, free, total in self.utilization
            if total > 0
        ]
        if len(samples) < 2:
            return None
        num = den = 0.0
        for (ts0, free, total), (ts1, _, _) in zip(samples, samples[1:]):
            dt = max(0.0, ts1 - ts0)
            num += (free / total) * dt
            den += dt
        return num / den if den > 0 else None
