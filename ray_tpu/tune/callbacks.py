"""Tune run callbacks + experiment-tracking integrations.

Reference: tune's Callback seam (python/ray/tune/callback.py) and the
AIR integrations (python/ray/air/integrations/wandb.py, mlflow.py) —
per-trial lifecycle hooks that loggers and trackers attach to. The
wandb/mlflow adapters follow the Optuna-adapter pattern used across
this repo: when the library is installed its real client is driven;
otherwise a faithful in-module fake implements the same init/log/
finish (run/metric/param) surface so the adapter code path is
identical and testable in this zero-egress image.

Usage::

    tune.Tuner(
        trainable,
        run_config=tune.RunConfig(
            callbacks=[tune.JsonLoggerCallback(),
                       tune.WandbLoggerCallback(project="exp")],
        ),
        ...,
    )
"""

from __future__ import annotations

import json
import os
from typing import Any


class Callback:
    """Per-trial lifecycle hooks (reference: tune.Callback). All hooks
    are optional; the controller warns-and-continues on callback
    exceptions (a logger bug degrades logging, not the run)."""

    def on_trial_start(self, trial_id: str, config: dict) -> None:
        pass

    def on_trial_result(
        self, trial_id: str, config: dict, result: dict
    ) -> None:
        pass

    def on_trial_complete(
        self, trial_id: str, result: "dict | None",
        error: "str | None" = None,
    ) -> None:
        pass

    def on_experiment_end(self, results: list) -> None:
        pass


class JsonLoggerCallback(Callback):
    """One JSONL of results per trial under the experiment dir
    (reference: tune's JsonLoggerCallback result.json)."""

    def __init__(self, exp_dir: "str | None" = None):
        self.exp_dir = exp_dir  # filled by the controller when None
        self._files: dict[str, Any] = {}

    def _file(self, trial_id: str):
        f = self._files.get(trial_id)
        if f is None:
            os.makedirs(self.exp_dir, exist_ok=True)
            f = open(
                os.path.join(self.exp_dir, f"{trial_id}.result.jsonl"),
                "a",
            )
            self._files[trial_id] = f
        return f

    def on_trial_result(self, trial_id, config, result):
        f = self._file(trial_id)
        f.write(json.dumps({"config": config, **result}, default=str))
        f.write("\n")
        f.flush()

    def on_experiment_end(self, results):
        for f in self._files.values():
            f.close()
        self._files = {}


# ----------------------------------------------------------- wandb
class _FakeWandbRun:
    def __init__(self, project, name, config):
        self.project = project
        self.name = name
        self.config = dict(config or {})
        self.logged: list[dict] = []
        self.finished = False

    def log(self, metrics: dict) -> None:
        self.logged.append(dict(metrics))

    def finish(self) -> None:
        self.finished = True


class _FakeWandb:
    """Faithful init/log/finish surface of the wandb client."""

    def __init__(self):
        self.runs: list[_FakeWandbRun] = []

    def init(self, project=None, name=None, config=None, **_kw):
        run = _FakeWandbRun(project, name, config)
        self.runs.append(run)
        return run


class WandbLoggerCallback(Callback):
    """Stream every trial's results to a wandb run (reference:
    air/integrations/wandb.py WandbLoggerCallback — one run per trial,
    config as run config, metrics via run.log)."""

    def __init__(self, project: str = "ray_tpu", *, _force_fake=False):
        self.project = project
        if _force_fake:
            self._wandb, self.using_fake = _FakeWandb(), True
        else:
            try:
                import wandb  # noqa: PLC0415

                self._wandb, self.using_fake = wandb, False
            except ImportError:
                self._wandb, self.using_fake = _FakeWandb(), True
        self._runs: dict[str, Any] = {}

    def on_trial_start(self, trial_id, config):
        # reinit="create_new": concurrent trials each keep a LIVE run —
        # legacy reinit=True finishes the previous run, silently
        # dropping every earlier trial's remaining metrics.
        self._runs[trial_id] = self._wandb.init(
            project=self.project, name=trial_id, config=config,
            reinit="create_new",
        )

    def on_trial_result(self, trial_id, config, result):
        run = self._runs.get(trial_id)
        if run is not None:
            run.log(
                {
                    k: v
                    for k, v in result.items()
                    if isinstance(v, (int, float))
                }
            )

    def on_trial_complete(self, trial_id, result, error=None):
        run = self._runs.pop(trial_id, None)
        if run is not None:
            run.finish()


# ---------------------------------------------------------- mlflow
class _FakeMlflowRunHandle:
    class _Info:
        def __init__(self, run_id):
            self.run_id = run_id

    def __init__(self, run_id):
        self.info = self._Info(run_id)


class _FakeMlflow:
    """Faithful experiment/run/log surface of the mlflow client,
    including run RESUMPTION by run_id (start_run(run_id=...))."""

    def __init__(self):
        self.experiment = None
        self.runs: list[dict] = []
        self._by_id: dict[str, dict] = {}
        self._active: "dict | None" = None

    def set_experiment(self, name):
        self.experiment = name

    def start_run(self, run_name=None, run_id=None):
        if run_id is not None:
            self._active = self._by_id[run_id]
            self._active["ended"] = False
        else:
            run_id = f"run{len(self.runs)}"
            self._active = {
                "run_id": run_id, "run_name": run_name,
                "params": {}, "metrics": [], "ended": False,
            }
            self.runs.append(self._active)
            self._by_id[run_id] = self._active
        return _FakeMlflowRunHandle(self._active["run_id"])

    def log_params(self, params):
        self._active["params"].update(params)

    def log_metrics(self, metrics, step=None):
        self._active["metrics"].append((step, dict(metrics)))

    def end_run(self):
        if self._active is not None:
            self._active["ended"] = True
            self._active = None


class MLflowLoggerCallback(Callback):
    """Per-trial MLflow runs with params + stepped metrics (reference:
    air/integrations/mlflow.py MLflowLoggerCallback)."""

    def __init__(
        self, experiment_name: str = "ray_tpu", *, _force_fake=False
    ):
        self.experiment_name = experiment_name
        if _force_fake:
            self._mlflow, self.using_fake = _FakeMlflow(), True
        else:
            try:
                import mlflow  # noqa: PLC0415

                self._mlflow, self.using_fake = mlflow, False
            except ImportError:
                self._mlflow, self.using_fake = _FakeMlflow(), True
        self._mlflow.set_experiment(self.experiment_name)
        self._run_ids: dict[str, str] = {}

    def on_trial_start(self, trial_id, config):
        # ONE mlflow run per trial, resumed by run_id on every later
        # report — mlflow's module API keeps a single active run, and
        # start_run(run_name=...) would CREATE a new run each call,
        # fragmenting a trial into per-point runs.
        run = self._mlflow.start_run(run_name=trial_id)
        self._run_ids[trial_id] = run.info.run_id
        self._mlflow.log_params(
            {k: str(v) for k, v in config.items()}
        )
        self._mlflow.end_run()

    def on_trial_result(self, trial_id, config, result):
        run_id = self._run_ids.get(trial_id)
        if run_id is None:
            return
        self._mlflow.start_run(run_id=run_id)
        self._mlflow.log_metrics(
            {
                k: float(v)
                for k, v in result.items()
                if isinstance(v, (int, float))
            },
            step=result.get("training_iteration"),
        )
        self._mlflow.end_run()

    def on_trial_complete(self, trial_id, result, error=None):
        self._run_ids.pop(trial_id, None)
