"""TPU-native LLM library (ray.llm equivalent).

The reference delegates engines to vLLM (reference:
python/ray/llm/_internal/serve/engines/vllm/vllm_models.py:234 passes
tensor_parallel_size through; vllm_engine.py gang-schedules workers on
placement groups). Here the engine is native: a static-shape JAX
prefill/decode pair over a slot-based KV cache (continuous batching), with
tensor parallelism as a pjit sharding of the same programs — no external
engine process.

- :class:`LLMEngine` — prefill + decode with continuous batching.
- :func:`build_llm_deployment` — serve integration.
- :func:`build_batch_inferencer` — Data integration (map_batches actors).
"""

from ray_tpu.llm.engine import LLMEngine, SamplingParams
from ray_tpu.llm.kv_cache import forward_prefill, forward_decode, init_kv_cache
from ray_tpu.llm.serve_integration import build_llm_deployment
from ray_tpu.llm.batch import build_batch_inferencer
from ray_tpu.llm.tokenizer import ByteTokenizer

__all__ = [
    "ByteTokenizer",
    "LLMEngine",
    "SamplingParams",
    "build_batch_inferencer",
    "build_llm_deployment",
    "forward_decode",
    "forward_prefill",
    "init_kv_cache",
]

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu('llm')
del _rlu
