"""Batch inference over ray_tpu.data (ray.llm batch equivalent).

Reference: python/ray/llm/_internal/batch/ runs a vLLM processor inside
Data's actor-pool map; here the processor is a callable class holding an
LLMEngine, handed to Dataset.map_batches(compute="actors") so the
streaming executor scales engine actors and keeps blocks flowing.
"""

from __future__ import annotations

from ray_tpu.llm.engine import LLMEngine, SamplingParams
from ray_tpu.llm.tokenizer import ByteTokenizer


def build_batch_inferencer(
    model="tiny",
    *,
    engine_kwargs: dict | None = None,
    tokenizer=None,
    prompt_column: str = "prompt",
    output_column: str = "generated",
    max_tokens: int = 32,
    temperature: float = 0.0,
):
    """Returns a class for ds.map_batches(..., compute="actors").

    Each data actor owns one engine; a batch's prompts run through the
    engine's continuous batcher together.
    """
    ek = engine_kwargs or {}
    tok = tokenizer

    class LLMInferencer:
        def __init__(self):
            self.engine = LLMEngine(model, **ek)
            self.tokenizer = tok or ByteTokenizer()
            self.sampling = SamplingParams(
                max_tokens=max_tokens, temperature=temperature
            )

        def __call__(self, batch: dict) -> dict:
            prompts = [
                self.tokenizer.encode(p) if isinstance(p, str) else list(p)
                for p in batch[prompt_column]
            ]
            outs = self.engine.generate(prompts, self.sampling)
            batch[output_column] = [self.tokenizer.decode(o) for o in outs]
            return batch

    LLMInferencer.__name__ = f"LLMInferencer_{model}"
    return LLMInferencer

