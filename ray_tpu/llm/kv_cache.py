"""KV-cached forward passes for autoregressive decoding.

TPU-first: both programs have fully static shapes. The cache is a
[L, B, S_max, Hkv, Dh] ring of slots; prefill writes one slot's prompt,
decode advances every active slot by one token. Padding/garbage cache
entries are never attended (position mask) and are overwritten as
generation proceeds, so no dynamic shapes or host-side cache surgery are
needed — the whole decode loop is two cached XLA programs.

The reference has no native engine (SURVEY.md §2.4: ray.llm wraps vLLM);
this module is the compute core its vLLM dependency provided.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import LlamaConfig, Params
from ray_tpu.ops.attention import causal_attention
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.rope import apply_rope, rope_frequencies

_NEG_INF = -2.0e38

KVCache = dict[str, jnp.ndarray]  # {"k": [L,B,S,Hkv,Dh], "v": same}


def init_kv_cache(
    cfg: LlamaConfig, max_batch: int, max_seq: int
) -> KVCache:
    shape = (cfg.n_layers, max_batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }


def _project_qkv(x, p, cfg):
    b, s, _ = x.shape
    dt = cfg.dtype
    h = rms_norm(x, p["attn_norm"])
    q = (h @ p["wq"].astype(dt)).reshape(b, s, cfg.n_heads, cfg.head_dim)
    k = (h @ p["wk"].astype(dt)).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    v = (h @ p["wv"].astype(dt)).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    return q, k, v


def _mlp(x, p, cfg):
    dt = cfg.dtype
    h = rms_norm(x, p["mlp_norm"])
    gate = jax.nn.silu(h @ p["w_gate"].astype(dt))
    up = h @ p["w_up"].astype(dt)
    return x + (gate * up) @ p["w_down"].astype(dt)


def forward_prefill(
    params: Params,
    tokens: jnp.ndarray,  # [1, S_pad] int32 (one slot's prompt, padded)
    cache: KVCache,
    slot: jnp.ndarray,  # scalar int32: which cache row to fill
    cfg: LlamaConfig,
    use_flash: bool = False,
) -> tuple[jnp.ndarray, KVCache]:
    """Run the prompt through the model, writing K/V into cache[:, slot].

    Returns logits [1, S_pad, V] (caller reads position true_len-1) and
    the updated cache. Padding tokens write garbage K/V beyond true_len —
    harmless: decode masks keys at positions > its own current length and
    overwrites them one by one. ``use_flash`` routes attention through the
    Pallas flash kernel (forward-only path, so no VJP needed).
    """
    seq = tokens.shape[1]
    cos, sin = rope_frequencies(cfg.head_dim, seq, cfg.rope_theta)
    x = params["tok_emb"].astype(cfg.dtype)[tokens]
    # The kernel accepts any length (blocks clamp to the largest divisor
    # of seq), but awkward lengths degrade: gate on the FITTED block
    # being MXU-friendly (>=128, multiple of 8) so prime-ish prompt
    # lengths keep the fused dense path instead of 1-wide Pallas tiles.
    from ray_tpu.ops.pallas.flash_attention import DEFAULT_BLOCK, _fit_block

    _blk = _fit_block(DEFAULT_BLOCK, seq)
    flash_ok = use_flash and seq >= 512 and _blk >= 128 and _blk % 8 == 0

    def attend(q, k, v):
        if flash_ok:
            from ray_tpu.ops.pallas import flash_attention

            # interpret mode runs the same kernel on CPU (tests).
            return flash_attention(
                q, k, v, interpret=jax.default_backend() != "tpu"
            )
        return causal_attention(q, k, v)

    def body(x, layer):
        p, k_row, v_row = layer
        q, k, v = _project_qkv(x, p, cfg)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        attn = attend(q, k, v)
        x = x + attn.reshape(x.shape) @ p["wo"].astype(cfg.dtype)
        x = _mlp(x, p, cfg)
        # [B=1, S, Hkv, Dh] → write into this layer's [Bmax, Smax, ...] row.
        k_row = jax.lax.dynamic_update_slice(
            k_row, k.astype(cfg.dtype), (slot, 0, 0, 0)
        )
        v_row = jax.lax.dynamic_update_slice(
            v_row, v.astype(cfg.dtype), (slot, 0, 0, 0)
        )
        return x, (k_row, v_row)

    x, (k_cache, v_cache) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"])
    )
    x = rms_norm(x, params["final_norm"])
    logits = (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
    return logits, {"k": k_cache, "v": v_cache}


def forward_decode(
    params: Params,
    tokens: jnp.ndarray,  # [B, 1] int32: current token of every slot
    cache: KVCache,
    positions: jnp.ndarray,  # [B] int32: position each token sits at
    cfg: LlamaConfig,
) -> tuple[jnp.ndarray, KVCache]:
    """One decode step for all slots. Returns logits [B, V] + cache."""
    x = params["tok_emb"].astype(cfg.dtype)[tokens]  # [B, 1, d]
    b = tokens.shape[0]
    max_seq = cache["k"].shape[2]
    # Table sized to the CACHE length, not cfg.max_seq: an engine may run
    # with a longer max_seq than the config default, and an out-of-range
    # gather would silently clamp to the last row (wrong rotations).
    cos, sin = rope_frequencies(cfg.head_dim, max_seq, cfg.rope_theta)

    # Keys at index > position are stale (padding or other requests'
    # leftovers); mask them. Index == position is this step's token.
    key_idx = jnp.arange(max_seq)[None, :]  # [1, S]
    mask = key_idx > positions[:, None]  # [B, S] True = masked

    def write_row(row, val, pos):
        # row [Smax, Hkv, Dh], val [1, Hkv, Dh]
        return jax.lax.dynamic_update_slice(row, val, (pos, 0, 0))

    def body(x, layer):
        p, k_row, v_row = layer  # k_row [B, Smax, Hkv, Dh]
        q, k, v = _project_qkv(x, p, cfg)  # q [B,1,H,Dh]
        pos2d = positions[:, None]  # [B, 1]
        q = apply_rope(q, cos, sin, positions=pos2d)
        k = apply_rope(k, cos, sin, positions=pos2d)
        k_row = jax.vmap(write_row)(k_row, k.astype(cfg.dtype), positions)
        v_row = jax.vmap(write_row)(v_row, v.astype(cfg.dtype), positions)

        n_rep = cfg.n_heads // cfg.n_kv_heads
        kk = jnp.repeat(k_row, n_rep, axis=2)  # [B, S, H, Dh]
        vv = jnp.repeat(v_row, n_rep, axis=2)
        scale = cfg.head_dim**-0.5
        logits = (
            jnp.einsum("bqhd,bkhd->bhqk", q, kk, preferred_element_type=jnp.float32)
            * scale
        )  # [B, H, 1, S]
        logits = jnp.where(mask[:, None, None, :], _NEG_INF, logits)
        probs = jax.nn.softmax(logits, axis=-1).astype(cfg.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, vv)
        x = x + attn.reshape(b, 1, -1) @ p["wo"].astype(cfg.dtype)
        x = _mlp(x, p, cfg)
        return x, (k_row, v_row)

    x, (k_cache, v_cache) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"])
    )
    x = rms_norm(x, params["final_norm"])
    logits = (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
    return logits[:, 0], {"k": k_cache, "v": v_cache}
