"""Byte-level tokenizer: zero-dependency default for tests and demos.

The reference relies on HF tokenizers via vLLM; any object with
encode(str)->list[int] / decode(list[int])->str (e.g. a transformers
tokenizer) can be passed wherever a tokenizer is accepted — this is the
built-in fallback with a 256-byte vocabulary plus specials.
"""

from __future__ import annotations


class ByteTokenizer:
    PAD = 256
    BOS = 257
    EOS = 258
    vocab_size = 259

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8"))
        return [self.BOS] + ids if add_bos else ids

    def decode(self, ids: list[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", "replace")
