"""Paged KV cache: block-table attention over a fixed page pool.

The dense cache (`llm/kv_cache.py`) allocates max_batch × max_seq slots
up front, so HBM cost ignores actual sequence lengths. This module is
the vLLM-style alternative the reference gets from its serving engine
(reference: ray.llm passes engine_kwargs straight to vLLM,
python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_models.py:234 —
block_size / num_gpu_blocks are vLLM's page knobs):

- One **page pool** per layer: [L, num_pages, Hkv, page_size, Dh]
  (HEAD-major: the Pallas decode kernel reads one KV head's page tile
  as a contiguous slice — measured ~40% faster than page-major; the
  XLA fallback folds the layout into its einsums, see
  _gather_page_attention).
  Capacity is a token budget (num_pages × page_size), independent of
  how many requests share it or how long each runs.
- A **block table** per request: the ordered list of page ids holding
  its tokens. Tables live on the host (numpy, tiny) and ship to the
  device each step as a [B, max_pages] int32 array.
- **Decode** gathers each slot's pages (jnp.take along the page axis) and
  runs masked attention over the gathered window — static shapes, XLA
  fuses gather+attention; no pallas needed until page counts get large.
- **Prefill** computes K/V with the normal dense program and scatters
  them into freshly-allocated pages.
- **Prefix sharing**: full pages whose token prefix hashes equal an
  existing page's are refcounted and reused instead of re-written —
  identical prompt heads across requests occupy one set of pages
  (memory dedup; compute dedup via chunked prefill is future work).

TPU-first notes: everything under jit has static shapes — the gather
width is the per-call max_pages bucket, masked per-slot by true length.
Pool pages are never zeroed on free; stale data is unreachable because
attention masks beyond each slot's length and tables are host-owned.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.llama import LlamaConfig
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.rope import apply_rope, rope_frequencies

_NEG_INF = -2.0e38

PagedKV = dict[str, jnp.ndarray]  # {"k","v": [L, num_pages, Hkv, P, Dh]}

# The live memory-ledger claim for this process's KV pool (one pool
# per process); init_paged_kv closes and replaces it on re-init.
_KV_REG = None


def init_paged_kv(
    cfg: LlamaConfig, num_pages: int, page_size: int = 64
) -> PagedKV:
    shape = (cfg.n_layers, num_pages, cfg.n_kv_heads, page_size, cfg.head_dim)
    kv = {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }
    # Claim the pool in the device-memory ledger (runtime/memory.py):
    # the KV pages are serving's big fixed HBM tenant (the token-budget
    # analogue of the trainer's param/optimizer claim). The live
    # Registration is retained module-level so the claim has an owner:
    # a re-created pool explicitly retires the previous claim instead
    # of relying on tag replacement (TPU404).
    from ray_tpu.runtime import memory as _rmem

    global _KV_REG
    if _KV_REG is not None:
        _KV_REG.close()
    _KV_REG = _rmem.track(
        "llm.paged_kv", kind="kv_cache",
        nbytes=int(kv["k"].nbytes + kv["v"].nbytes),
    )
    _rmem.tag_arrays("llm.paged_kv", "kv_cache", kv)
    return kv


class PageAllocator:
    """Host-side page bookkeeping: free list, per-page refcounts, and the
    prefix-hash → page map for sharing (reference capability: vLLM's
    BlockSpaceManager + prefix caching)."""

    def __init__(self, num_pages: int, page_size: int):
        # `num_pages` counts USABLE pages. Physical page 0 is the DUMP
        # page: inactive decode slots' table entries clamp to it, so
        # their (discarded) writes land somewhere no request owns. The
        # pool must therefore be created with num_pages + 1 physical
        # pages (the engine does).
        self.num_pages = num_pages
        self.page_size = page_size
        self._free: list[int] = list(range(1, num_pages + 1))
        self._refs = np.zeros(num_pages + 1, np.int32)
        # prefix-hash → page id; hash covers ALL tokens up to and
        # including this page (k/v of a position depend on the whole
        # prefix, so equal hash ⇒ identical page contents).
        self._prefix_pages: dict[int, int] = {}
        self._page_hash: dict[int, int] = {}  # page id → its prefix hash

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def alloc(self) -> int:
        page = self._free.pop()
        self._refs[page] = 1
        return page

    def share(self, page: int) -> int:
        self._refs[page] += 1
        return page

    def release(self, page: int) -> None:
        self._refs[page] -= 1
        if self._refs[page] == 0:
            h = self._page_hash.pop(page, None)
            if h is not None and self._prefix_pages.get(h) == page:
                del self._prefix_pages[h]
            self._free.append(page)

    def lookup_prefix(self, prefix_hash: int) -> int | None:
        return self._prefix_pages.get(prefix_hash)

    def register_prefix(self, prefix_hash: int, page: int) -> None:
        self._prefix_pages[prefix_hash] = page
        self._page_hash[page] = prefix_hash


def prefix_hashes(tokens: list[int], page_size: int) -> list[int]:
    """One hash per FULL page, each covering tokens[0 : (i+1)*page]."""
    out = []
    for end in range(page_size, len(tokens) + 1, page_size):
        out.append(hash(tuple(tokens[:end])))
    return out


# ------------------------------------------------------------- programs
# One source of truth for the per-layer blocks: divergence between the
# paged and dense cache paths would silently change decode results.
from ray_tpu.llm.kv_cache import _mlp, _project_qkv  # noqa: E402


def _gather_page_attention(q, k_pool, v_pool, page_index, mask, cfg):
    """Dense masked attention over gathered pool pages — the XLA
    fallback shared by decode/verify and chunked prefill (one body: a
    numerics change here changes every gather-path caller at once).

    q: [B, Q, H, Dh]; page_index: [B, n_pages] int32 (>= 0);
    mask: [B, Q, window] bool, True = hidden. Returns [B, Q, H, Dh].
    """
    b, q_len = q.shape[0], q.shape[1]
    hkv = cfg.n_kv_heads
    n_rep = cfg.n_heads // hkv
    dh = cfg.head_dim
    n_pages = page_index.shape[1]
    page_size = k_pool.shape[2]
    window = n_pages * page_size
    # Head-major pool gathers to [B, n_pages, Hkv, P, Dh]; the page and
    # cell dims contract/flatten INSIDE the einsums — no materialized
    # layout transpose and no GQA repeat (q is grouped by KV head
    # instead: head h = g*n_rep + r).
    kk = jnp.take(k_pool, page_index, axis=0)
    vv = jnp.take(v_pool, page_index, axis=0)
    qg = q.reshape(b, q_len, hkv, n_rep, dh)
    scale = dh**-0.5
    logits = (
        jnp.einsum(
            "bqgrd,bngpd->bgrqnp", qg, kk,
            preferred_element_type=jnp.float32,
        )
        * scale
    ).reshape(b, hkv, n_rep, q_len, window)
    logits = jnp.where(
        mask[:, None, None, :, :], _NEG_INF, logits
    )
    probs = jax.nn.softmax(logits, axis=-1).astype(cfg.dtype)
    attn = jnp.einsum(
        "bgrqnp,bngpd->bqgrd",
        probs.reshape(b, hkv, n_rep, q_len, n_pages, page_size),
        vv,
    )
    return attn.reshape(b, q_len, cfg.n_heads, dh)


@partial(
    jax.jit,
    static_argnames=("cfg", "n_write_pages"),
    donate_argnames=("pool",),
)
def paged_prefill(
    params,
    tokens: jnp.ndarray,  # [1, S_pad] int32
    pool: PagedKV,
    pages: jnp.ndarray,  # [n_write_pages] int32 page ids for this prompt
    cfg: LlamaConfig,
    n_write_pages: int,
):
    """Dense prompt pass; K/V scattered into `pages` of the pool.

    S_pad must equal n_write_pages * page_size (caller pads). `pages`
    covers the WHOLE padded prompt including shared-prefix pages: their
    content is rewritten with byte-identical values (K/V at position i
    depend only on tokens <= i), so sharing needs no scatter mask.
    Returns (logits [1, S_pad, V] fp32, pool).
    """
    seq = tokens.shape[1]
    page_size = pool["k"].shape[3]
    cos, sin = rope_frequencies(cfg.head_dim, seq, cfg.rope_theta)
    x = params["tok_emb"].astype(cfg.dtype)[tokens]

    from ray_tpu.ops.attention import causal_attention

    def body(x, layer):
        p, k_pool, v_pool = layer  # k_pool [num_pages, Hkv, P, Dh]
        q, k, v = _project_qkv(x, p, cfg)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        attn = causal_attention(q, k, v)
        x = x + attn.reshape(x.shape) @ p["wo"].astype(cfg.dtype)
        x = _mlp(x, p, cfg)
        # [1, S, Hkv, Dh] → [n_pages, P, Hkv, Dh] scatter at page ids.
        kp = k.astype(cfg.dtype).reshape(
            n_write_pages, page_size, cfg.n_kv_heads, cfg.head_dim
        ).transpose(0, 2, 1, 3)
        vp = v.astype(cfg.dtype).reshape(
            n_write_pages, page_size, cfg.n_kv_heads, cfg.head_dim
        ).transpose(0, 2, 1, 3)
        k_pool = k_pool.at[pages].set(kp)
        v_pool = v_pool.at[pages].set(vp)
        return x, (k_pool, v_pool)

    x, (k_pool, v_pool) = jax.lax.scan(
        body, x, (params["blocks"], pool["k"], pool["v"])
    )
    x = rms_norm(x, params["final_norm"])
    logits = (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
    return logits, {"k": k_pool, "v": v_pool}


@partial(
    jax.jit,
    static_argnames=("cfg", "n_write_pages", "chunk_pages"),
    donate_argnames=("pool",),
)
def paged_prefill_chunk(
    params,
    tokens: jnp.ndarray,  # [1, C] int32, C = chunk_pages * page_size
    pool: PagedKV,
    pages: jnp.ndarray,  # [n_write_pages] int32: the FULL context table
    start: jnp.ndarray,  # [] int32: global position of tokens[0, 0]
    cfg: LlamaConfig,
    n_write_pages: int,
    chunk_pages: int,
):
    """One prefill CHUNK: compute K/V for ``tokens`` at positions
    ``start .. start+C-1``, scatter them into the chunk's slice of
    ``pages``, and attend each chunk query over the whole context so
    far (earlier chunks' pages + this chunk, causal within the chunk).

    Splitting prefill this way is what lets the engine interleave a
    long prompt with decode steps instead of stalling every in-flight
    request for the prompt's full dense pass (reference capability:
    vLLM's chunked prefill, which ray.llm buys via engine_kwargs).
    ``start`` must be page-aligned; K/V of a position depend only on
    tokens <= it, so chunking is mathematically exact.

    Returns (logits [1, C, V] fp32, pool).
    """
    c = tokens.shape[1]
    page_size = pool["k"].shape[3]
    window = n_write_pages * page_size
    cos, sin = rope_frequencies(cfg.head_dim, window, cfg.rope_theta)
    pos = start + jnp.arange(c, dtype=jnp.int32)[None, :]  # [1, C]
    x = params["tok_emb"].astype(cfg.dtype)[tokens]
    chunk_slice = jax.lax.dynamic_slice(
        pages, [start // page_size], [chunk_pages]
    )
    key_idx = jnp.arange(window)[None, None, :]
    mask = key_idx > pos[:, :, None]  # [1, C, window]

    def body(x, layer):
        p, k_pool, v_pool = layer
        q, k, v = _project_qkv(x, p, cfg)  # [1, C, H, Dh]
        q = apply_rope(q, cos, sin, positions=pos)
        k = apply_rope(k, cos, sin, positions=pos)
        kp = k.astype(cfg.dtype).reshape(
            chunk_pages, page_size, cfg.n_kv_heads, cfg.head_dim
        ).transpose(0, 2, 1, 3)
        vp = v.astype(cfg.dtype).reshape(
            chunk_pages, page_size, cfg.n_kv_heads, cfg.head_dim
        ).transpose(0, 2, 1, 3)
        k_pool = k_pool.at[chunk_slice].set(kp)
        v_pool = v_pool.at[chunk_slice].set(vp)
        attn = _gather_page_attention(
            q, k_pool, v_pool, pages[None, :], mask, cfg
        )
        x = x + attn.reshape(1, c, -1) @ p["wo"].astype(cfg.dtype)
        x = _mlp(x, p, cfg)
        return x, (k_pool, v_pool)

    x, (k_pool, v_pool) = jax.lax.scan(
        body, x, (params["blocks"], pool["k"], pool["v"])
    )
    x = rms_norm(x, params["final_norm"])
    logits = (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
    return logits, {"k": k_pool, "v": v_pool}


def paged_decode(
    params,
    tokens: jnp.ndarray,  # [B, 1] int32
    pool: PagedKV,
    block_tables: jnp.ndarray,  # [B, max_pages] int32 (-1 = unused)
    positions: jnp.ndarray,  # [B] int32: position this token writes at
    temperature: jnp.ndarray,  # [B] fp32 (0 = greedy)
    rng_key: jnp.ndarray,
    cfg: LlamaConfig,
    use_kernel: bool = False,
):
    """One decode step over the page pool — exactly the K=1 case of
    :func:`paged_verify` (one source of truth for the page-attention
    body; divergence between cache paths would silently change decode
    results). Sampling happens ON DEVICE — the host receives [B]
    token ids, not [B, V] logits.

    Returns (sampled [B] int32, logits [B, V] fp32, pool).
    """
    sampled, _accept, _rej, logits, pool = paged_verify(
        params, tokens, pool, block_tables, positions, temperature,
        rng_key, cfg=cfg, use_kernel=use_kernel, stochastic=False,
    )
    return sampled[:, 0], logits, pool


@partial(
    jax.jit,
    static_argnames=("cfg", "use_kernel", "stochastic"),
    donate_argnames=("pool",),
)
def paged_verify(
    params,
    tokens: jnp.ndarray,  # [B, K] int32: next token + K-1 draft tokens
    pool: PagedKV,
    block_tables: jnp.ndarray,  # [B, max_pages] int32 (-1 = unused)
    positions: jnp.ndarray,  # [B] int32: position tokens[:, 0] writes at
    temperature: jnp.ndarray,  # [B] fp32 (0 = greedy)
    rng_key: jnp.ndarray,
    cfg: LlamaConfig,
    use_kernel: bool = False,
    stochastic: bool = True,
):
    """Speculative verify step: process K tokens per slot in ONE pass
    (reference capability: vLLM's speculative/prompt-lookup decoding,
    the serving engine behind ray.llm). tokens[:, 0] is the ordinary
    next token; tokens[:, 1:] are HOST-PROPOSED draft tokens (n-gram
    prompt lookup — no draft model). The engine accepts the longest
    prefix the model agrees with, advancing up to K tokens per
    dispatch.

    Acceptance inputs are computed ON DEVICE for every slot:

    - greedy slots (temp 0): ``accept[b, j]`` = the model's argmax
      after position j equals draft token j+1 — exactly the original
      host comparison.
    - stochastic slots: exact rejection sampling against the
      prompt-lookup draft's delta distribution q(x) = 1{x = draft}:
      accept with probability min(1, p(draft)/q(draft)) = p(draft),
      and on rejection emit a sample from the residual
      norm(max(p - q, 0)) — i.e. p with the draft token masked out.
      The emitted stream is distributed EXACTLY as sampling from p
      (Leviathan et al.; vLLM's rejection sampler).

    Rejected drafts need no rollback: a rejected position's K/V cell is
    re-written by the next step's scatter BEFORE any query attends that
    position (scatter precedes gather within each layer, and the causal
    mask hides cells beyond each query's position until then).

    Returns (sampled [B, K] int32, accept [B, K-1] bool,
    rej [B, K-1] int32 residual samples, logits [B, V] fp32 for
    position 0, pool).
    """
    b, kk_w = tokens.shape
    x = params["tok_emb"].astype(cfg.dtype)[tokens]  # [B, K, d]
    page_size = pool["k"].shape[3]
    max_pages = block_tables.shape[1]
    window = max_pages * page_size
    cos, sin = rope_frequencies(cfg.head_dim, window, cfg.rope_theta)

    pos2d = positions[:, None] + jnp.arange(kk_w)[None, :]  # [B, K]
    key_idx = jnp.arange(window)[None, None, :]
    mask = key_idx > pos2d[:, :, None]  # [B, K, window]

    page_of = jnp.minimum(pos2d // page_size, max_pages - 1)  # [B, K]
    off_of = pos2d % page_size
    # Physical pages for each write. Two overflow routes to the dump
    # page 0 (whose contents nobody attends): inactive slots
    # (table -1) and draft positions past the table window — near
    # max_seq a K-wide step can extend beyond capacity, and clamping
    # into the LAST page would corrupt live cells.
    write_pages = jnp.maximum(
        jnp.take_along_axis(block_tables, page_of, axis=1), 0
    )
    write_pages = jnp.where(pos2d < window, write_pages, 0)  # [B, K]

    def body(x, layer):
        p, k_pool, v_pool = layer
        q, k, v = _project_qkv(x, p, cfg)  # [B, K, H, Dh]
        q = apply_rope(q, cos, sin, positions=pos2d)
        k = apply_rope(k, cos, sin, positions=pos2d)

        # Scatter all K cells per slot (drafts may span a page
        # boundary — each position indexes its own physical page).
        # Advanced indices at dims 0 and 2 with the Hkv slice
        # between: result dims are [B, K, Hkv, Dh], matching k.
        k_pool = k_pool.at[write_pages, :, off_of, :].set(
            k.astype(cfg.dtype)
        )
        v_pool = v_pool.at[write_pages, :, off_of, :].set(
            v.astype(cfg.dtype)
        )

        if use_kernel:
            # Pallas path: pages read in place, GQA-grouped, per-slot
            # length early-exit (see ops/pallas/paged_attention.py).
            from ray_tpu.ops.pallas.paged_attention import paged_attention

            attn = paged_attention(
                q, k_pool, v_pool, block_tables, positions,
                n_kv_heads=cfg.n_kv_heads,
                interpret=jax.default_backend() != "tpu",
            )
        else:
            attn = _gather_page_attention(
                q, k_pool, v_pool, jnp.maximum(block_tables, 0),
                mask, cfg,
            )
        x = x + attn.reshape(b, kk_w, -1) @ p["wo"].astype(cfg.dtype)
        x = _mlp(x, p, cfg)
        return x, (k_pool, v_pool)

    x, (k_pool, v_pool) = jax.lax.scan(
        body, x, (params["blocks"], pool["k"], pool["v"])
    )
    x = rms_norm(x, params["final_norm"])
    logits = (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)

    # Per-position sampling: greedy for temp 0, temperature draw
    # otherwise (the full-p sample — used for position 0, for the
    # bonus token when a whole draft is accepted, and for every
    # position on greedy slots).
    flat = logits.reshape(b * kk_w, -1)
    temp_flat = jnp.repeat(temperature, kk_w)
    keys = jax.random.split(rng_key, b * kk_w)
    greedy = jnp.argmax(flat, axis=-1)
    drawn = jax.vmap(jax.random.categorical)(
        keys, flat / jnp.maximum(temp_flat, 1e-6)[:, None]
    )
    sampled = jnp.where(temp_flat > 0.0, drawn, greedy).astype(jnp.int32)
    sampled = sampled.reshape(b, kk_w)

    if kk_w > 1:
        # Draft acceptance inputs (see docstring). Positions 0..K-2
        # judge draft tokens 1..K-1.
        drafts = tokens[:, 1:]  # [B, K-1]
        head = logits[:, : kk_w - 1]  # [B, K-1, V] fp32
        head_argmax = jnp.argmax(head, axis=-1)
        acc_greedy = head_argmax == drafts
        if stochastic:
            temp_c = jnp.maximum(temperature, 1e-6)[:, None, None]
            probs = jax.nn.softmax(head / temp_c, axis=-1)
            p_draft = jnp.take_along_axis(
                probs, drafts[:, :, None], axis=-1
            )[..., 0]  # [B, K-1]
            u = jax.random.uniform(
                jax.random.fold_in(rng_key, 1), (b, kk_w - 1)
            )
            accept = jnp.where(
                temperature[:, None] > 0.0, u < p_draft, acc_greedy
            )
            # Residual emission on rejection: p with the draft token
            # masked (stochastic); the plain argmax for greedy
            # (identical to the original host behavior — rejection
            # implies argmax != draft).
            masked = head + jnp.where(
                jax.nn.one_hot(drafts, head.shape[-1], dtype=jnp.bool_),
                _NEG_INF,
                0.0,
            )
            rej_keys = jax.random.split(
                jax.random.fold_in(rng_key, 2), b * (kk_w - 1)
            )
            rej_drawn = jax.vmap(jax.random.categorical)(
                rej_keys,
                (masked / temp_c).reshape(b * (kk_w - 1), -1),
            ).reshape(b, kk_w - 1)
            rej = jnp.where(
                temperature[:, None] > 0.0,
                rej_drawn,
                head_argmax,
            ).astype(jnp.int32)
        else:
            # All-greedy batch (static flag from the engine): the
            # rejection tensors — a [B, K-1, V] softmax, one_hot mask,
            # and b*(K-1) categorical draws — would be dead weight on
            # every dispatch.
            accept = acc_greedy
            rej = head_argmax.astype(jnp.int32)
    else:
        accept = jnp.zeros((b, 0), jnp.bool_)
        rej = jnp.zeros((b, 0), jnp.int32)
    # Only position 0's logits ever reach the host (top_k fallback);
    # shipping [B, K, V] would multiply that transfer by K for nothing.
    return (
        sampled,
        accept,
        rej,
        logits[:, 0],
        {"k": k_pool, "v": v_pool},
    )


def propose_ngram_draft(
    context: list[int] | np.ndarray, k: int, ngram: int = 2
) -> list[int]:
    """Prompt-lookup drafting (host side, no draft model): find the
    most recent earlier occurrence of the last ``ngram`` tokens and
    propose the ``k`` tokens that followed it. Returns [] when no match
    — the verify pass then degenerates to a normal decode step.

    Vectorized: one numpy sliding-window comparison per call — this
    runs per greedy slot per decode step, so a Python slice-compare
    scan would put O(context) interpreter work on the serial host path
    in front of every dispatch."""
    ctx = np.asarray(context, dtype=np.int64)
    n = len(ctx)
    if n < ngram + 1 or k <= 0:
        return []
    tail = ctx[n - ngram:]
    # Window starts eligible as a match: exclude the tail itself.
    hits = ctx[: n - 1 - (ngram - 1)] == tail[0]
    for j in range(1, ngram):
        hits = hits & (ctx[j: n - 1 - (ngram - 1) + j] == tail[j])
    idx = np.nonzero(hits)[0]
    if idx.size == 0:
        return []
    start = int(idx[-1])  # rightmost: recent repetition predicts best
    follow = ctx[start + ngram: start + ngram + k]
    return follow.astype(int).tolist()


def sample_on_device(
    logits: jnp.ndarray,  # [B, V] fp32
    temperature: jnp.ndarray,  # [B] fp32, 0 = greedy
    rng_key: jnp.ndarray,
) -> jnp.ndarray:
    """Greedy / temperature sampling without shipping logits to host.
    Both paths are computed and the per-slot temperature selects —
    cheaper than a lax.cond at [B,V] widths and keeps one fused program."""
    greedy = jnp.argmax(logits, axis=-1)
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    keys = jax.random.split(rng_key, logits.shape[0])
    drawn = jax.vmap(jax.random.categorical)(keys, logits / temp)
    return jnp.where(temperature > 0.0, drawn, greedy).astype(jnp.int32)
