"""Serve integration: an LLM deployment wrapping the engine.

Reference shape: ray.llm builds Serve deployments around vLLM engines
(reference: python/ray/llm/_internal/serve/, serve/llm/). Here the replica
owns an LLMEngine; requests are enqueued into the engine's continuous
batcher and a single background pump drives step() while any request is
in flight, so concurrent callers share decode batches instead of queueing
behind each other.
"""

from __future__ import annotations

import asyncio
import time

from ray_tpu.llm.engine import LLMEngine, SamplingParams
from ray_tpu.llm.tokenizer import ByteTokenizer


class LLMServer:
    """Deployment callable. Use via build_llm_deployment()."""

    def __init__(self, model="tiny", engine_kwargs=None, tokenizer=None):
        self.engine = LLMEngine(model, **(engine_kwargs or {}))
        self.tokenizer = tokenizer or ByteTokenizer()
        self._waiters: dict[str, asyncio.Future] = {}
        # request_id → queue of token-delta lists; None marks the end of
        # a stream (the feed for SSE streaming responses).
        self._streams: dict[str, asyncio.Queue] = {}
        # request_id → engine timing of a finished streamed request
        # (the pump parks it here; stream() reads it after the None
        # sentinel to emit the prefill/decode spans).
        self._timings: dict[str, dict] = {}
        self._pump_task: asyncio.Task | None = None
        # Deployment label for telemetry: replicas learn their own name
        # from the first request's context (the engine pump itself runs
        # outside any request).
        self._deployment = "llm"

    def _note_deployment(self) -> str:
        from ray_tpu.serve.context import get_request_context

        dep = get_request_context().deployment
        if dep:
            self._deployment = dep
        return self._deployment

    async def _pump(self):
        from ray_tpu.serve import telemetry as stel

        loop = asyncio.get_running_loop()
        tel_on = stel.enabled()
        try:
            while self.engine.has_unfinished():
                # step() is blocking JAX compute (seconds on a first
                # compile) — run it off-loop so this replica keeps
                # answering RPCs, including the controller's health polls.
                finished = await loop.run_in_executor(None, self.engine.step)
                for rid, toks in self.engine.drain_deltas().items():
                    q = self._streams.get(rid)
                    if q is not None:
                        q.put_nowait(toks)
                for fin in finished:
                    fut = self._waiters.pop(fin["request_id"], None)
                    if fut is not None and not fut.done():
                        fut.set_result(fin)
                    q = self._streams.get(fin["request_id"])
                    if q is not None:
                        self._timings[fin["request_id"]] = fin
                        q.put_nowait(None)
                if tel_on:
                    # Saturation gauges at step cadence: decode-slot
                    # occupancy + paged-KV pool utilization — the
                    # engine-side signals the SLO autoscaler reads.
                    eng = self.engine
                    stel.set_engine_gauges(
                        self._deployment,
                        active=len(eng._active),
                        max_batch=eng.max_batch,
                        pages_free=(
                            eng.alloc.free_pages
                            if eng.kv == "paged" else None
                        ),
                        pages_total=(
                            eng.alloc.num_pages
                            if eng.kv == "paged" else None
                        ),
                    )
        # tpulint: allow(broad-except reason=the pump failure is fanned out to every pending waiter future and stream queue - nothing is swallowed)
        except Exception as e:  # noqa: BLE001
            # Fail every pending caller rather than hanging them forever.
            waiters, self._waiters = self._waiters, {}
            for fut in waiters.values():
                if not fut.done():
                    fut.set_exception(e)
            streams, self._streams = self._streams, {}
            for q in streams.values():
                q.put_nowait(e)

    def _ensure_pump(self):
        if self._pump_task is None or self._pump_task.done():
            self._pump_task = asyncio.ensure_future(self._pump())

    async def generate(
        self,
        prompt: str | list[int],
        max_tokens: int = 64,
        temperature: float = 0.0,
        stop_token_ids: tuple = (),
    ) -> dict:
        tokens = (
            self.tokenizer.encode(prompt) if isinstance(prompt, str) else prompt
        )
        sampling = SamplingParams(
            max_tokens=max_tokens,
            temperature=temperature,
            stop_token_ids=tuple(stop_token_ids),
        )
        from ray_tpu.serve import telemetry as stel

        deployment = self._note_deployment()
        rid = self.engine.add_request(tokens, sampling)
        fut = asyncio.get_running_loop().create_future()
        self._waiters[rid] = fut
        self._ensure_pump()
        fin = await fut
        out = fin["tokens"]
        timing = fin.get("timing") or {}
        if stel.enabled():
            # serve:prefill / serve:decode under this request's replica
            # span (the contextvar survives the await — same task).
            stel.record_engine_phases(deployment, timing, len(out))
        return {
            "tokens": out,
            "text": self.tokenizer.decode(out),
            "num_generated": len(out),
            "ttft_s": timing.get("ttft_s"),
        }

    async def stream(
        self,
        prompt: str | list[int],
        max_tokens: int = 64,
        temperature: float = 0.0,
        stop_token_ids: tuple = (),
    ):
        """Async generator: yields one dict per decode-step delta as the
        engine produces tokens (reference: ray.llm streaming chat
        completions over vLLM's AsyncLLMEngine generator)."""
        tokens = (
            self.tokenizer.encode(prompt) if isinstance(prompt, str) else prompt
        )
        sampling = SamplingParams(
            max_tokens=max_tokens,
            temperature=temperature,
            stop_token_ids=tuple(stop_token_ids),
        )
        from ray_tpu.serve import telemetry as stel

        deployment = self._note_deployment()
        tel_on = stel.enabled()
        rid = self.engine.add_request(tokens, sampling, stream=True)
        q: asyncio.Queue = asyncio.Queue()
        self._streams[rid] = q
        self._ensure_pump()
        produced = 0
        last_ts = time.time()
        try:
            while True:
                delta = await q.get()
                if delta is None:
                    break
                if isinstance(delta, BaseException):
                    raise delta
                produced += len(delta)
                if tel_on:
                    # Per-delta decode spans ride the high-rate sampler
                    # so a long generation can't storm the recorder.
                    now = time.time()
                    stel.record_token_span(
                        deployment, last_ts, now - last_ts, len(delta)
                    )
                    last_ts = now
                yield {
                    "tokens": delta,
                    "text": self.tokenizer.decode(delta),
                    "num_generated": produced,
                }
        finally:
            fin = self._timings.pop(rid, None)
            if tel_on and fin is not None:
                stel.record_engine_phases(
                    deployment, fin.get("timing"), produced
                )
            self._streams.pop(rid, None)
            # Client gone (or stream complete — then this is a no-op):
            # free the engine slot instead of decoding to max_tokens for
            # nobody.
            self.engine.abort_request(rid)

    async def stats(self) -> dict:
        """Engine serving counters (reference shape: the vLLM metrics
        ray.llm deployments expose) — callable as a deployment method:
        HTTP {"method": "stats"} or handle.options(method_name=
        "stats"). Async via the executor: engine.stats() takes the
        engine lock, which the pump holds across whole step() calls —
        grabbing it on the event loop would freeze the replica for a
        step (minutes on a first compile)."""
        return await asyncio.get_running_loop().run_in_executor(
            None, self.engine.stats
        )

    async def __call__(self, request: dict):
        body = request.get("body") if isinstance(request, dict) else None
        if isinstance(body, dict):
            # HTTP ingress shape: parameters ride in the JSON body.
            request = body
        if request.get("method") == "stats":
            return await self.stats()
        if request.get("stream"):
            return self.stream(
                request["prompt"],
                max_tokens=request.get("max_tokens", 64),
                temperature=request.get("temperature", 0.0),
            )
        return await self.generate(
            request["prompt"],
            max_tokens=request.get("max_tokens", 64),
            temperature=request.get("temperature", 0.0),
        )


def build_llm_deployment(
    model="tiny",
    *,
    num_replicas: int = 1,
    engine_kwargs: dict | None = None,
    tokenizer=None,
    ray_actor_options: dict | None = None,
):
    """Returns a bound serve deployment; pass to serve.run()."""
    from ray_tpu import serve

    dep = serve.deployment(
        LLMServer,
        num_replicas=num_replicas,
        ray_actor_options=ray_actor_options or {},
        max_ongoing_requests=32,
    )
    return dep.bind(model, engine_kwargs, tokenizer)
