"""LLMEngine: continuous-batching inference over the static-shape
prefill/decode programs.

Plays the role of vLLM's engine in the reference stack (SURVEY.md §2.4:
ray.llm passes TP/PP sizes to vLLM and gang-schedules its workers).
TPU-native shape: tensor parallelism is not worker processes — it is the
same two XLA programs pjit-sharded over a mesh's 'tp' axis, so adding
chips changes a sharding annotation, not the orchestration.

Slot model: the KV cache holds `max_batch` rows. add_request() parks
requests in a FIFO; step() admits queued requests into free slots
(one prefill each, bucketed to power-of-two lengths to bound compile
count) and then advances all active slots with one decode program.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.llm.kv_cache import forward_decode, forward_prefill, init_kv_cache
from ray_tpu.models.llama import LlamaConfig, PRESETS, init_params, param_logical_axes


@dataclass(frozen=True)
class SamplingParams:
    max_tokens: int = 64
    temperature: float = 0.0  # 0 = greedy
    top_k: int = 0  # 0 = full vocab
    stop_token_ids: tuple = ()
    seed: int = 0


@dataclass
class _Request:
    request_id: str
    prompt: list[int]
    sampling: SamplingParams
    out_tokens: list = field(default_factory=list)
    slot: int = -1
    position: int = 0  # index the NEXT token will be written at
    last_token: int = 0
    done: bool = False
    pages: list = field(default_factory=list)  # paged mode: block table
    # Request-path timing (wall clock), the feed for the serve:prefill /
    # serve:decode spans and TTFT/TPOT histograms. First-write-wins so a
    # preemption's recompute re-admission never resets TTFT.
    submit_ts: float = 0.0
    prefill_start_ts: float = 0.0
    first_token_ts: float = 0.0
    finish_ts: float = 0.0


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


class LLMEngine:
    def __init__(
        self,
        model: str | LlamaConfig = "tiny",
        *,
        max_batch: int = 4,
        max_seq: int | None = None,
        mesh=None,
        params=None,
        seed: int = 0,
        kv: str = "paged",  # "paged" (block-table pool) | "dense" (slab)
        page_size: int = 64,
        num_pages: int | None = None,
        speculate: int = 0,  # draft tokens per step (prompt lookup)
        prefill_chunk: int | None = None,  # tokens per prefill chunk
        prefill_delay_s: float = 0.0,  # chaos: injected TTFT (tests)
    ):
        cfg = PRESETS[model] if isinstance(model, str) else model
        self.cfg = cfg
        self.max_batch = max_batch
        self.max_seq = max_seq or cfg.max_seq
        self.mesh = mesh
        if params is None:
            params = init_params(jax.random.key(seed), cfg)
        if mesh is not None:
            from ray_tpu.parallel.sharding import shard_pytree

            params = shard_pytree(params, mesh, param_logical_axes(cfg))
        self.params = params
        if kv not in ("paged", "dense"):
            raise ValueError(f"kv must be 'paged' or 'dense', got {kv!r}")
        self.kv = kv
        self.page_size = page_size
        self.prefill_delay_s = float(prefill_delay_s)

        # Flash prefill on a bare TPU backend; under a mesh the dense
        # path keeps XLA's SPMD partitioner in charge.
        use_flash = mesh is None and jax.default_backend() == "tpu"
        if speculate and kv != "paged":
            raise ValueError("speculative decoding needs kv='paged'")
        if prefill_chunk is not None and kv != "paged":
            raise ValueError("chunked prefill needs kv='paged'")
        self.speculate = int(speculate)
        if kv == "paged":
            from ray_tpu.llm.paged_kv import (
                PageAllocator,
                init_paged_kv,
                paged_decode,
                paged_prefill,
                paged_verify,
            )

            # Default token budget matches the dense slab so existing
            # callers see identical capacity; serving deployments pass a
            # smaller num_pages to run memory-bound admission (the
            # point: many variable-length requests share one budget).
            if num_pages is None:
                num_pages = max(
                    (max_batch * self.max_seq) // page_size, max_batch
                )
            self.alloc = PageAllocator(num_pages, page_size)
            # +1: physical page 0 is the allocator's dump page.
            if (
                mesh is not None
                and mesh.shape.get("tp", 1) > 1
                and cfg.n_kv_heads % mesh.shape["tp"] == 0
            ):
                # Shard the pool on the KV-head dim over tp (the
                # head-major layout's natural TP split): each chip
                # holds 1/tp of the KV bytes — the reference's
                # tensor_parallel_size KV split — and the attention
                # einsums contract per-head, so SPMD needs no
                # resharding on the hot path. Allocated DIRECTLY
                # sharded (out_shardings on the zeros program): pools
                # are sized toward per-chip HBM x tp, so a transient
                # unsharded replica would OOM at init.
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                ns = NamedSharding(
                    mesh, P(None, None, "tp", None, None)
                )
                self.cache = jax.jit(
                    partial(
                        init_paged_kv, cfg, num_pages + 1, page_size
                    ),
                    out_shardings={"k": ns, "v": ns},
                )()
            else:
                self.cache = init_paged_kv(
                    cfg, num_pages + 1, page_size
                )
            self.max_pages_per_seq = -(-self.max_seq // page_size)
            # Pallas paged-attention kernel on a bare TPU backend (the
            # sharded path keeps XLA's SPMD partitioner in charge, like
            # use_flash above). RAY_TPU_PAGED_ATTN=0/1 overrides — =1
            # on CPU runs the kernel interpreted (parity tests).
            import os

            # tpulint: allow(TPU703 reason=emergency kernel off-switch read in library code that must work without a live runtime or config registry)
            env_flag = os.environ.get("RAY_TPU_PAGED_ATTN", "").strip()
            if env_flag in ("0", "1"):
                use_kernel = env_flag == "1"
            else:
                use_kernel = (
                    mesh is None and jax.default_backend() == "tpu"
                )
            self.paged_attn_kernel = use_kernel
            # Chunked prefill: a prompt longer than the chunk is
            # prefilled one page-aligned chunk per step(), interleaved
            # with decode — one long admission no longer stalls every
            # in-flight request for its full dense pass (reference
            # capability: vLLM chunked prefill behind ray.llm).
            if prefill_chunk is not None:
                prefill_chunk = max(
                    -(-prefill_chunk // page_size) * page_size, page_size
                )
            self.prefill_chunk = prefill_chunk
            self._prefilling: dict | None = None
            from ray_tpu.llm.paged_kv import paged_prefill_chunk

            self._prefill_chunk_fn = partial(paged_prefill_chunk, cfg=cfg)
            self._prefill_paged = partial(paged_prefill, cfg=cfg)
            self._decode_paged = partial(
                paged_decode, cfg=cfg, use_kernel=use_kernel
            )
            self._verify_paged = partial(
                paged_verify, cfg=cfg, use_kernel=use_kernel
            )
            self._step_key = jax.random.key(seed)
            self._temps = np.zeros((max_batch,), np.float32)
        else:
            self.prefill_chunk = None
            self._prefilling = None
            self.cache = init_kv_cache(cfg, max_batch, self.max_seq)
            # donate the cache slab: without donation every functional
            # .at[].set update forces XLA to copy the whole cache.
            self._prefill = jax.jit(
                partial(forward_prefill, cfg=cfg, use_flash=use_flash),
                donate_argnums=(2,),
            )
            self._decode = jax.jit(
                partial(forward_decode, cfg=cfg), donate_argnums=(2,)
            )
        self._queue: list[_Request] = []
        self._active: dict[int, _Request] = {}  # slot → request
        self._free = list(range(max_batch))
        self._ids = itertools.count()
        self._rng = np.random.default_rng(seed)
        # Host mirrors of the decode inputs, one entry per slot.
        self._tokens = np.zeros((max_batch, 1), np.int32)
        self._positions = np.zeros((max_batch,), np.int32)
        # add_request may run on a different thread than step() (the serve
        # pump runs step in an executor); guard the queue/slot state.
        self._lock = threading.Lock()
        # Per-request tokens emitted since the last drain_deltas() call —
        # the feed for streaming responses (reference shape: vLLM's
        # per-step RequestOutput deltas). Only requests added with
        # stream=True record deltas, so batch callers don't accumulate
        # tokens nobody drains.
        self._deltas: dict[str, list[int]] = {}
        self._stream_ids: set[str] = set()
        # Serving observability counters (reference: the vLLM stats
        # ray.llm surfaces — requests, tokens, acceptance, preemption).
        self._stats = {
            "requests_submitted": 0,
            "requests_finished": 0,
            "tokens_generated": 0,
            "draft_tokens_proposed": 0,
            "draft_tokens_accepted": 0,
            "requests_aborted": 0,
            "preemptions": 0,
            "prefill_chunks": 0,
        }

    # ------------------------------------------------------ request API
    def add_request(
        self,
        prompt: list[int],
        sampling: SamplingParams | None = None,
        request_id: str | None = None,
        stream: bool = False,
    ) -> str:
        if len(prompt) >= self.max_seq:
            raise ValueError(
                f"prompt length {len(prompt)} >= max_seq {self.max_seq}"
            )
        sampling = sampling or SamplingParams()
        if self.kv == "paged":
            # Reject requests the pool could NEVER hold (prompt plus its
            # full max_tokens growth) at submission — admitting one and
            # crashing mid-decode would take every in-flight request
            # down with it.
            P = self.page_size
            worst = min(len(prompt) + sampling.max_tokens, self.max_seq)
            pad = min(
                max(_bucket(worst), P), self.max_pages_per_seq * P
            )
            if pad // P > self.alloc.num_pages:
                raise ValueError(
                    f"prompt+max_tokens needs {pad // P} pages but the "
                    f"pool holds {self.alloc.num_pages}; raise num_pages "
                    "or lower max_tokens"
                )
        rid = request_id or f"req-{next(self._ids)}"
        with self._lock:
            self._stats["requests_submitted"] += 1
            if stream:
                self._stream_ids.add(rid)
            self._queue.append(
                _Request(
                    rid, list(prompt), sampling,
                    submit_ts=time.time(),
                )
            )
        return rid

    def _begin_prefill(self, req: _Request) -> None:
        """Mark prefill start (first-write-wins) and apply the injected
        prefill delay (the ``prefill_delay_s`` engine kwarg, or the
        RAY_TPU_LLM_PREFILL_DELAY env knob) — a deterministic TTFT
        injection the serve-tracing tests bound spans against."""
        if req.prefill_start_ts == 0.0:
            req.prefill_start_ts = time.time()
        delay = self.prefill_delay_s
        if delay <= 0:
            from ray_tpu._private import config

            delay = config.get("LLM_PREFILL_DELAY")
        if delay > 0:
            time.sleep(delay)

    def has_unfinished(self) -> bool:
        return bool(
            self._queue or self._active or self._prefilling is not None
        )

    def _sample(self, logits: np.ndarray, s: SamplingParams) -> int:
        if s.temperature <= 0.0:
            return int(logits.argmax())
        logits = logits / s.temperature
        if s.top_k:
            kth = np.partition(logits, -s.top_k)[-s.top_k]
            logits = np.where(logits < kth, -np.inf, logits)
        logits = logits - logits.max()
        probs = np.exp(logits)
        probs /= probs.sum()
        return int(self._rng.choice(len(probs), p=probs))

    def _finish_if_done(self, req: _Request, finished: list[dict]) -> bool:
        """Evaluate stop conditions on req's latest token (shared by the
        prefill-sampled token and decode-sampled tokens)."""
        s = req.sampling
        tok = req.out_tokens[-1]
        if not (
            tok in s.stop_token_ids
            or len(req.out_tokens) >= s.max_tokens
            or req.position >= self.max_seq - 1
        ):
            return False
        if tok in s.stop_token_ids:
            req.out_tokens.pop()  # don't return the stop token
            d = self._deltas.get(req.request_id)
            if d and d[-1] == tok:
                d.pop()
        req.done = True
        req.finish_ts = time.time()
        self._stats["requests_finished"] += 1
        self._stream_ids.discard(req.request_id)
        finished.append(
            {
                "request_id": req.request_id,
                "prompt": req.prompt,
                "tokens": req.out_tokens,
                "timing": self._request_timing(req),
            }
        )
        if req.slot in self._active:
            del self._active[req.slot]
            self._free.append(req.slot)
        self._release_pages(req)
        return True

    @staticmethod
    def _request_timing(req: _Request) -> dict:
        """Wall-clock phase breakdown of one finished request: queue
        (submit→prefill start), prefill (prefill start→first token),
        decode (first token→finish), plus TTFT — the serve telemetry
        span/histogram feed."""
        t = {
            "submit_ts": req.submit_ts,
            "prefill_start_ts": req.prefill_start_ts,
            "first_token_ts": req.first_token_ts,
            "finish_ts": req.finish_ts,
        }
        if req.submit_ts and req.prefill_start_ts:
            t["queue_s"] = max(0.0, req.prefill_start_ts - req.submit_ts)
        if req.prefill_start_ts and req.first_token_ts:
            t["prefill_s"] = max(
                0.0, req.first_token_ts - req.prefill_start_ts
            )
        if req.submit_ts and req.first_token_ts:
            t["ttft_s"] = max(0.0, req.first_token_ts - req.submit_ts)
        if req.first_token_ts and req.finish_ts:
            t["decode_s"] = max(0.0, req.finish_ts - req.first_token_ts)
        return t

    def _release_pages(self, req: _Request) -> None:
        if self.kv == "paged":
            for pg in req.pages:
                self.alloc.release(pg)
            req.pages = []

    def _admit(self, finished: list[dict]) -> None:
        while self._queue and self._free:
            if self.kv == "paged":
                if not self._admit_one_paged(finished):
                    return
                continue
            req = self._queue.pop(0)
            slot = self._free.pop(0)
            self._begin_prefill(req)
            pad = min(_bucket(len(req.prompt)), self.max_seq)
            tokens = np.zeros((1, pad), np.int32)
            tokens[0, : len(req.prompt)] = req.prompt
            logits, self.cache = self._prefill(
                self.params, jnp.asarray(tokens), self.cache,
                jnp.int32(slot),
            )
            self._post_prefill(req, slot, logits, len(req.prompt), finished)

    def _post_prefill(
        self, req, slot, logits, ctx_len, finished, logit_idx=None
    ) -> None:
        """Shared dense/paged tail of admission: sample the next token
        from the context's last logits, activate, run stop checks.
        ctx_len is the true (unpadded) prefilled length — prompt plus
        any tokens generated before a preemption. logit_idx overrides
        the row to sample from (chunked prefill: the last token's index
        LOCAL to the final chunk)."""
        last = np.asarray(
            logits[0, ctx_len - 1 if logit_idx is None else logit_idx]
        )
        req.slot = slot
        req.position = ctx_len
        if req.first_token_ts == 0.0:
            req.first_token_ts = time.time()
        req.last_token = self._sample(last, req.sampling)
        self._stats["tokens_generated"] += 1  # the prefill-sampled token
        req.out_tokens.append(req.last_token)
        if req.request_id in self._stream_ids:
            self._deltas.setdefault(req.request_id, []).append(
                req.last_token
            )
        self._active[slot] = req
        # The prefill-sampled token can already hit max_tokens=1 or a
        # stop token; finishing here frees the slot for this _admit
        # loop itself.
        if not self._finish_if_done(req, finished):
            self._tokens[slot, 0] = req.last_token
            self._positions[slot] = req.position
            if self.kv == "paged":
                self._temps[slot] = req.sampling.temperature

    def _admit_one_paged(self, finished: list[dict]) -> bool:
        """Admit the head of the queue if its pages fit the pool —
        MEMORY-bound admission (the dense engine is slot-bound). Returns
        False when the pool cannot hold the next request yet."""
        from ray_tpu.llm.paged_kv import prefix_hashes

        if self._prefilling is not None:
            # One chunked prefill at a time: its pages are committed and
            # its chunks are the per-step prefill budget already.
            return False
        P = self.page_size
        req = self._queue[0]
        # Full context: the prompt plus anything generated before a
        # preemption (recompute-style resume). req.prompt stays pristine.
        context = list(req.prompt) + list(req.out_tokens)
        pad = min(
            max(_bucket(len(context)), P),
            self.max_pages_per_seq * P,
        )
        need_pages = pad // P
        # Prefix sharing: leading FULL pages whose token prefix matches a
        # live page are reused (refcounted), not re-allocated.
        hashes = prefix_hashes(context, P)
        shared: list[int] = []
        for h in hashes:
            pg = self.alloc.lookup_prefix(h)
            if pg is None:
                break
            shared.append(pg)
        if need_pages > self.alloc.num_pages:
            # Would never fit even with the pool empty — a config error,
            # not backpressure; failing loud beats spinning forever.
            self._queue.pop(0)
            raise RuntimeError(
                f"prompt needs {need_pages} pages but the pool holds "
                f"{self.alloc.num_pages}; raise num_pages or page_size"
            )
        if need_pages - len(shared) > self.alloc.free_pages:
            return False
        self._queue.pop(0)
        slot = self._free.pop(0)
        self._begin_prefill(req)
        pages = [self.alloc.share(pg) for pg in shared]
        for i in range(len(shared), need_pages):
            pg = self.alloc.alloc()
            if i < len(hashes):
                self.alloc.register_prefix(hashes[i], pg)
            pages.append(pg)
        req.pages = pages
        if (
            self.prefill_chunk is not None
            and len(context) > self.prefill_chunk
        ):
            # Long prompt: hold the slot and prefill one chunk per
            # step(), interleaved with decode. Chunks cover only the
            # context's own pages (ceil(ctx/P)); the bucket's growth
            # pages stay unwritten until decode reaches them.
            self._prefilling = {
                "req": req,
                "slot": slot,
                "context": context,
                "pages": np.asarray(pages, np.int32),
                "next_start": 0,
                "ctx_pad": -(-len(context) // P) * P,
                "need_pages": need_pages,
            }
            self._prefill_step(finished)
            return True
        tokens = np.zeros((1, pad), np.int32)
        tokens[0, : len(context)] = context
        # Prefill rewrites shared pages with byte-identical values (K/V
        # at position i depend only on tokens <= i) — idempotent, so no
        # write mask is needed.
        logits, self.cache = self._prefill_paged(
            self.params,
            jnp.asarray(tokens),
            self.cache,
            jnp.asarray(np.asarray(pages, np.int32)),
            n_write_pages=need_pages,
        )
        self._post_prefill(req, slot, logits, len(context), finished)
        return True

    def _prefill_step(self, finished: list[dict]) -> None:
        """Advance the in-flight chunked prefill by ONE chunk; on the
        final chunk, sample the first token and activate the slot."""
        st = self._prefilling
        assert st is not None
        P = self.page_size
        context = st["context"]
        start = st["next_start"]
        end = min(start + self.prefill_chunk, st["ctx_pad"])
        tokens = np.zeros((1, end - start), np.int32)
        valid = context[start: min(end, len(context))]
        tokens[0, : len(valid)] = valid
        logits, self.cache = self._prefill_chunk_fn(
            self.params,
            jnp.asarray(tokens),
            self.cache,
            jnp.asarray(st["pages"]),
            jnp.int32(start),
            n_write_pages=st["need_pages"],
            chunk_pages=(end - start) // P,
        )
        st["next_start"] = end
        self._stats["prefill_chunks"] += 1
        if end >= st["ctx_pad"]:
            self._prefilling = None
            # ctx_len-1 always falls in the final chunk: ctx_pad is
            # page-aligned, so ctx_pad - len(context) < P <= chunk.
            self._post_prefill(
                st["req"], st["slot"], logits, len(context), finished,
                logit_idx=len(context) - 1 - start,
            )

    def step(self) -> list[dict]:
        """Admit + one decode step. Returns finished request dicts."""
        finished: list[dict] = []
        with self._lock:
            if self._prefilling is not None:
                # Continue the in-flight chunked prefill: one chunk per
                # step bounds the stall it adds to this step's decodes.
                self._prefill_step(finished)
            self._admit(finished)
            if not self._active:
                return finished
            if self.kv == "paged":
                self._step_paged(finished)
                return finished

            logits, self.cache = self._decode(
                self.params,
                jnp.asarray(self._tokens),
                self.cache,
                jnp.asarray(self._positions),
            )
            logits = np.asarray(logits)
            for slot, req in list(self._active.items()):
                tok = self._sample(logits[slot], req.sampling)
                self._record_token(req, tok, finished)
        return finished

    def _record_token(self, req, tok: int, finished: list[dict]) -> None:
        req.position += 1
        self._stats["tokens_generated"] += 1
        req.out_tokens.append(tok)
        if req.request_id in self._stream_ids:
            self._deltas.setdefault(req.request_id, []).append(tok)
        req.last_token = tok
        self._tokens[req.slot, 0] = tok
        self._positions[req.slot] = req.position
        self._finish_if_done(req, finished)

    def _preempt(self, req: _Request) -> None:
        """vLLM-style recompute preemption: free the pages + slot and
        requeue at the FRONT; re-admission prefills the request's full
        context (prompt + generated so far), so generation resumes
        exactly where it stopped. req.prompt itself is never mutated —
        finished dicts must echo the prompt the caller submitted."""
        self._stats["preemptions"] += 1
        self._release_pages(req)
        if req.slot in self._active:
            del self._active[req.slot]
            self._free.append(req.slot)
        req.slot = -1
        self._queue.insert(0, req)

    def _step_paged(self, finished: list[dict]) -> None:
        P = self.page_size
        K = 1 + self.speculate
        # Grow block tables to cover every position this step may write
        # ([position, position + K - 1] with speculation); exhausted
        # pool → preempt the youngest active request until pages fit.
        for slot, req in list(self._active.items()):
            if req.slot == -1 or req.done:
                continue
            # Clamp to the table width: near max_seq a K-wide step may
            # reach past capacity — the kernel routes those writes to
            # the dump page and _finish_if_done stops the request at
            # max_seq before any overflow token is kept.
            needed = min(
                (req.position + K - 1) // P + 1, self.max_pages_per_seq
            )
            while len(req.pages) < needed and req.slot != -1:
                if self.alloc.free_pages == 0:
                    victims = [
                        r for r in self._active.values() if r is not req
                    ]
                    if not victims:
                        self._preempt(req)
                        break
                    self._preempt(victims[-1])
                else:
                    req.pages.append(self.alloc.alloc())
        if not self._active:
            return

        tables = np.full(
            (self.max_batch, self.max_pages_per_seq), -1, np.int32
        )
        for slot, req in self._active.items():
            tables[slot, : len(req.pages)] = req.pages
        self._step_key, sub = jax.random.split(self._step_key)
        if self.speculate:
            self._step_paged_speculative(tables, sub, finished)
            return
        sampled, logits, self.cache = self._decode_paged(
            self.params,
            jnp.asarray(self._tokens),
            self.cache,
            jnp.asarray(tables),
            jnp.asarray(self._positions),
            jnp.asarray(self._temps),
            sub,
        )
        sampled = np.asarray(sampled)  # [B] ints — the only transfer
        host_logits = None
        for slot, req in list(self._active.items()):
            if req.sampling.top_k and req.sampling.temperature > 0:
                # top-k needs host logic; transfer logits lazily, once.
                # (top_k with temperature 0 IS greedy — the on-device
                # argmax already answered it; don't ship [B,V] for it.)
                if host_logits is None:
                    host_logits = np.asarray(logits)
                tok = self._sample(host_logits[slot], req.sampling)
            else:
                tok = int(sampled[slot])
            self._record_token(req, tok, finished)

    def _step_paged_speculative(self, tables, sub, finished) -> None:
        """Prompt-lookup speculative step (reference capability: vLLM
        speculative decoding behind ray.llm): verify K = 1 + speculate
        positions per slot in one dispatch and accept the longest
        draft prefix the model agrees with. Greedy slots accept on
        argmax equality (bit-identical to plain decode); stochastic
        slots use exact rejection sampling computed on device (see
        paged_kv.paged_verify) so their emitted stream is distributed
        exactly as plain temperature sampling. top_k slots run with an
        empty draft (their position-0 output is a normal decode step).

        Acceptance is one vectorized mismatch-argmax over [B, K-1] —
        not a per-slot interpreted loop on the serial dispatch path."""
        from ray_tpu.llm.paged_kv import propose_ngram_draft

        K = 1 + self.speculate
        toks = np.zeros((self.max_batch, K), np.int32)
        toks[:, 0] = self._tokens[:, 0]
        draft_len = np.zeros((self.max_batch,), np.int32)
        for slot, req in self._active.items():
            if req.sampling.top_k and req.sampling.temperature > 0:
                continue  # host-sampled: no draft
            draft = propose_ngram_draft(
                req.prompt + req.out_tokens, K - 1
            )
            if draft:
                draft_len[slot] = len(draft)
                self._stats["draft_tokens_proposed"] += len(draft)
                toks[slot, 1: 1 + len(draft)] = draft

        # Static flag: an all-greedy batch (the common speculative
        # configuration) skips the rejection-sampling tensors entirely
        # — at most two compiled variants, like use_kernel.
        any_stochastic = any(
            r.sampling.temperature > 0 and not r.sampling.top_k
            for r in self._active.values()
        )
        sampled, accept, rej, logits, self.cache = self._verify_paged(
            self.params,
            jnp.asarray(toks),
            self.cache,
            jnp.asarray(tables),
            jnp.asarray(self._positions),
            jnp.asarray(self._temps),
            sub,
            stochastic=any_stochastic,
        )
        sampled = np.asarray(sampled)  # [B, K]
        accept = np.asarray(accept)  # [B, K-1] bool
        rej = np.asarray(rej)  # [B, K-1]
        # Vectorized acceptance: n_acc[b] = index of the first rejected
        # (or absent) draft position.
        stop = ~accept
        stop |= np.arange(K - 1)[None, :] >= draft_len[:, None]
        n_acc = np.where(stop.any(axis=1), stop.argmax(axis=1), K - 1)
        host_logits = None
        for slot, req in list(self._active.items()):
            if req.sampling.top_k and req.sampling.temperature > 0:
                if host_logits is None:
                    host_logits = np.asarray(logits)  # [B, V]: pos 0
                tok = self._sample(host_logits[slot], req.sampling)
                self._record_token(req, tok, finished)
                continue
            na = int(n_acc[slot])
            # Accepted drafts verbatim, then the boundary token: the
            # residual sample if a draft was REJECTED there, the full-p
            # sample if the draft simply ran out (or none existed).
            emit = list(toks[slot, 1: 1 + na])
            if na < draft_len[slot]:
                emit.append(int(rej[slot, na]))
            else:
                emit.append(int(sampled[slot, na]))
            for idx, tok in enumerate(emit):
                self._record_token(req, int(tok), finished)
                if idx < na:
                    # Count acceptance by tokens actually EMITTED —
                    # verified drafts discarded when the request
                    # finishes mid-emit must not inflate the rate.
                    self._stats["draft_tokens_accepted"] += 1
                if req.done:
                    break

    def abort_request(self, request_id: str) -> bool:
        """Drop a request (queued or active), freeing its slot — the
        client-disconnect path for streaming (reference: vLLM engine
        abort_request). Safe to call after completion (returns False)."""
        with self._lock:
            self._stream_ids.discard(request_id)
            self._deltas.pop(request_id, None)
            st = self._prefilling
            if st is not None and st["req"].request_id == request_id:
                # Mid-chunked-prefill abort: free the held slot + pages
                # and drop the chunk state.
                self._prefilling = None
                self._free.append(st["slot"])
                self._release_pages(st["req"])
                self._stats["requests_aborted"] += 1
                return True
            for i, r in enumerate(self._queue):
                if r.request_id == request_id:
                    del self._queue[i]
                    self._stats["requests_aborted"] += 1
                    return True
            for slot, r in list(self._active.items()):
                if r.request_id == request_id:
                    r.done = True
                    del self._active[slot]
                    self._free.append(slot)
                    self._release_pages(r)
                    self._stats["requests_aborted"] += 1
                    return True
        return False

    def stats(self) -> dict:
        """Serving counters + live occupancy (reference shape: the
        vLLM engine stats ray.llm's deployments surface): request and
        token totals, speculative proposal/acceptance, preemptions,
        chunked-prefill progress, and the pool/slot occupancy."""
        with self._lock:
            out = dict(self._stats)
            out["active_requests"] = len(self._active)
            out["queued_requests"] = len(self._queue)
            out["prefilling"] = self._prefilling is not None
            if self.kv == "paged":
                out["pages_total"] = self.alloc.num_pages
                out["pages_free"] = self.alloc.free_pages
            if out["draft_tokens_proposed"]:
                out["draft_acceptance_rate"] = round(
                    out["draft_tokens_accepted"]
                    / out["draft_tokens_proposed"],
                    4,
                )
        return out

    def drain_deltas(self) -> dict[str, list[int]]:
        """Return and clear per-request tokens emitted since the last
        call — the streaming feed (callers pair it with step()'s finished
        list to know when a request's stream ends)."""
        with self._lock:
            out, self._deltas = self._deltas, {}
        return out

    def generate(
        self,
        prompts: list[list[int]],
        sampling: SamplingParams | None = None,
    ) -> list[list[int]]:
        """Synchronous convenience: run all prompts to completion."""
        order = {}
        for i, p in enumerate(prompts):
            order[self.add_request(p, sampling)] = i
        results: list = [None] * len(prompts)
        while self.has_unfinished():
            for fin in self.step():
                results[order[fin["request_id"]]] = fin["tokens"]
        return results
