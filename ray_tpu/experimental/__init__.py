"""Experimental APIs (reference: python/ray/experimental/ — the
declarative collective-group API on actor handles and the GPU-object /
tensor-transport manager, here TPU-objects)."""

from ray_tpu.experimental.collective import (
    create_collective_group,
    destroy_collective_group,
)
from ray_tpu.experimental.tensor_transport import (
    free_tensors,
    tensor_meta,
)

__all__ = [
    "create_collective_group",
    "destroy_collective_group",
    "free_tensors",
    "tensor_meta",
]
