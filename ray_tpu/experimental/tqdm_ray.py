"""Distributed progress bars (reference: python/ray/experimental/
tqdm_ray.py — worker-side tqdm shims report through the runtime and the
driver renders aggregated bars without interleaving).

Worker side: ``tqdm(iterable, ...)`` publishes rate-limited progress
snapshots to the head's "tqdm" pubsub channel. Driver side:
``enable_display()`` subscribes and renders one line per live bar to
stderr (plain lines, no cursor games — safe under pytest and log
capture)."""

from __future__ import annotations

import sys
import time
import uuid


class tqdm:
    """Drop-in minimal tqdm: iterable wrapper or manual update()."""

    def __init__(
        self,
        iterable=None,
        desc: str = "",
        total: int | None = None,
        position: int | None = None,  # accepted for API compat
        flush_interval_s: float = 0.5,
    ):
        self._iterable = iterable
        self.desc = desc
        self.total = total
        if total is None and iterable is not None:
            try:
                self.total = len(iterable)
            except TypeError:
                pass
        self.n = 0
        self._uuid = uuid.uuid4().hex[:12]
        self._flush_interval = flush_interval_s
        self._last_flush = 0.0
        self._closed = False

    # -- protocol ------------------------------------------------------
    def __iter__(self):
        try:
            for item in self._iterable:
                yield item
                self.update(1)
        finally:
            # Runs on break/exception too (GeneratorExit lands at the
            # yield) so the display always sees the bar finish.
            self.close()

    def update(self, n: int = 1):
        self.n += n
        now = time.monotonic()
        if now - self._last_flush >= self._flush_interval:
            self._last_flush = now
            self._publish(done=False)

    def set_description(self, desc: str):
        self.desc = desc

    def close(self):
        if not self._closed:
            self._closed = True
            self._publish(done=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- transport -----------------------------------------------------
    def _publish(self, done: bool):
        try:
            import asyncio

            import ray_tpu.api as api

            rt = api._runtime
            if rt.core is None:
                return
            msg = {
                "uuid": self._uuid,
                "desc": self.desc,
                "n": self.n,
                "total": self.total,
                "done": done,
                "src": rt.core.addr,
            }
            coro = rt.core.head.call("publish", channel="tqdm", msg=msg)
            try:
                running = asyncio.get_running_loop()
            except RuntimeError:
                running = None
            if running is rt.loop:
                # Already ON the runtime loop (async actor/task code):
                # blocking here would deadlock — fire and forget.
                asyncio.ensure_future(coro)
            else:
                rt.run(coro, timeout=5)
        # tpulint: allow(broad-except reason=progress publishing is best-effort; raising or logging from inside the bar-update path would corrupt the very output it decorates)
        except Exception:  # noqa: BLE001 - progress is best-effort
            pass


# {"head_addr": str, "out": sink} — re-calling swaps the sink, and a new
# cluster (different head) gets a fresh subscription.
_display: dict = {}


def _render_payload(payload):
    if payload.get("channel") != "tqdm":
        return
    # Coalesced ticks arrive as a "batch" list; render each.
    for msg in payload.get("batch") or [payload.get("msg", {})]:
        _render_msg(msg)


def _render_msg(msg):
    total = msg.get("total")
    frac = (
        f"{msg['n']}/{total}" if total else str(msg.get("n", 0))
    )
    state = "done" if msg.get("done") else "…"
    print(
        f"[{msg.get('desc') or msg.get('uuid', '?')}] {frac} {state}",
        file=_display.get("out", sys.stderr),
        flush=True,
    )


def enable_display(out=None) -> None:
    """Driver-side: subscribe to the tqdm channel and print progress
    lines as they arrive. Safe to call again — the latest ``out`` wins,
    and a new cluster re-subscribes."""
    import ray_tpu.api as api

    rt = api._runtime
    _display["out"] = out or sys.stderr
    if _display.get("head_addr") == rt.core.head_addr:
        return  # already subscribed on this cluster; sink swapped above

    async def subscribe():
        from ray_tpu._private import rpc

        conn = await rpc.connect(
            rt.core.head_addr, on_push=_render_payload
        )
        await conn.call("subscribe", channel="tqdm")
        return conn

    # The connection must be HELD: an unreferenced Connection is
    # garbage-collected, its recv task dies with it, and pushes stop
    # (GC timing made this a heisenbug).
    _display["conn"] = rt.run(subscribe())
    _display["head_addr"] = rt.core.head_addr
