"""TPU-object helpers: refs produced with ``tensor_transport`` keep their
payload in the producing actor's device-tensor store; these helpers
inspect and free them (reference:
python/ray/experimental/gpu_object_manager/gpu_object_manager.py)."""

from __future__ import annotations

from typing import Sequence


def _owner_call(ref, method: str, **kw):
    import ray_tpu.api as api

    rt = api._runtime

    async def call():
        conn = await rt.core._connect(ref.owner_addr)
        return await conn.call(method, oid_hex=ref.hex, **kw)

    return rt.run(call())


def tensor_meta(ref) -> dict | None:
    """Location metadata of a tensor-transport ref (None when the ref is
    not tensor-backed from this process's view)."""
    import ray_tpu.api as api

    rt = api._runtime
    rec = rt.core.memory.get(ref.hex)
    if rec is not None:
        return dict(rec[1]) if rec[0] == "tensor" else None
    reply = _owner_call(ref, "get_object")
    if reply.get("kind") == "tensor":
        return dict(reply["meta"])
    return None


def free_tensors(refs: Sequence) -> int:
    """Explicitly drop the device payloads behind tensor-transport refs
    (producers keep tensors pinned until freed). Returns the number
    actually freed."""
    import ray_tpu.api as api

    rt = api._runtime
    n = 0
    for ref in refs:
        rec = rt.core.memory.get(ref.hex)
        if rec is not None and rec[0] == "tensor":
            # This process owns the record: free directly.
            n += bool(rt.run(rt.core.free_tensor(ref.hex)))
        else:
            reply = _owner_call(ref, "free_tensor")
            n += bool(reply.get("ok"))
    return n
