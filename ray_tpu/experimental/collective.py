"""Declarative collective groups on actor handles (reference:
python/ray/experimental/collective/ — create_collective_group(actors)
used by the GPU-object transport; the imperative per-process API lives in
ray_tpu.collective, mirroring python/ray/util/collective/collective.py).

The driver assigns ranks by actor order and tells every actor to join the
named group; actors rendezvous through the head's KV store (the
reference's NCCLUniqueID named-actor store pattern,
nccl_collective_group.py:29–56, replaced by head-KV rendezvous)."""

from __future__ import annotations

from typing import Sequence


def _group_init(instance, world: int, rank: int, backend, group_name: str):
    from ray_tpu import collective

    collective.init_collective_group(
        world, rank, backend=backend, group_name=group_name
    )
    return rank


def _group_destroy(instance, group_name: str):
    from ray_tpu import collective

    if collective.is_group_initialized(group_name):
        collective.destroy_collective_group(group_name)
    return True


def _sys_call(handle, fn, *args):
    from ray_tpu.api import _submit_system_task

    return _submit_system_task(handle, fn, *args)


def create_collective_group(
    actors: Sequence,
    backend: str = "cpu",
    group_name: str = "default",
) -> None:
    """Join ``actors`` into one collective group; rank = position in the
    list. Blocks until every member has initialized."""
    import ray_tpu

    world = len(actors)
    refs = [
        _sys_call(a, _group_init, world, rank, backend, group_name)
        for rank, a in enumerate(actors)
    ]
    ray_tpu.get(refs, timeout=60)


def destroy_collective_group(
    actors: Sequence, group_name: str = "default"
) -> None:
    import ray_tpu

    refs = [_sys_call(a, _group_destroy, group_name) for a in actors]
    ray_tpu.get(refs, timeout=60)
