"""Dask-on-ray_tpu: execute dask task graphs on the cluster.

Reference: python/ray/util/dask/__init__.py — ``ray_dask_get``, a dask
scheduler that runs each graph task as a Ray task so dask collections
(dataframe/array/delayed) compute on the cluster. The TPU-native
equivalent: :func:`ray_tpu_dask_get` implements the dask *scheduler
protocol* (``get(dsk, keys)`` over the documented graph format — a
dict of key → task tuple/literal), so with dask installed you run

    dask.compute(obj, scheduler=ray_tpu_dask_get)

and WITHOUT dask the scheduler still executes hand-built graphs in the
same format (the graph spec is plain dicts/tuples — this module has no
dask import), which is how the zero-dask CI exercises it.

Execution: one ray_tpu task per graph node, submitted in dependency
order with upstream results passed as ObjectRefs — independent
subtrees run concurrently across the cluster, and intermediate results
move through the object store, never through the driver.
"""

from __future__ import annotations

from typing import Any


def _istask(x) -> bool:
    """Dask spec: a task is a tuple whose first element is callable."""
    return isinstance(x, tuple) and bool(x) and callable(x[0])


def _find_keys(expr, dsk, out: set) -> None:
    """Collect graph keys referenced inside a task expression. Keys are
    hashables present in the graph dict; per the dask spec they may
    appear nested in lists (tuples are tasks, not key containers,
    except tuple-keys which appear verbatim)."""
    if _istask(expr):
        for arg in expr[1:]:
            _find_keys(arg, dsk, out)
    elif isinstance(expr, list):
        for item in expr:
            _find_keys(item, dsk, out)
    else:
        try:
            if expr in dsk:
                out.add(expr)
        except TypeError:
            pass  # unhashable literal


def _execute_expr(expr, resolved: dict):
    """Evaluate a task expression with already-resolved dependencies
    substituted. Runs INSIDE the worker task."""
    if _istask(expr):
        fn = expr[0]
        args = [_execute_expr(a, resolved) for a in expr[1:]]
        return fn(*args)
    if isinstance(expr, list):
        return [_execute_expr(a, resolved) for a in expr]
    try:
        if expr in resolved:
            return resolved[expr]
    except TypeError:
        pass
    return expr


def _run_node(expr, dep_keys, *dep_values):
    """The remote task body: rebuild the resolved-deps mapping from
    positional ObjectRef arguments (the runtime resolves top-level
    refs) and evaluate the node expression."""
    return _execute_expr(expr, dict(zip(dep_keys, dep_values)))


def ray_tpu_dask_get(dsk: dict, keys, **kwargs) -> Any:
    """Dask scheduler protocol: compute ``keys`` from graph ``dsk``.

    ``keys`` may be a single key or (nested lists of) keys, per the
    dask ``get`` contract. Extra kwargs (dask passes scheduler hints)
    are accepted and ignored.
    """
    import ray_tpu

    run_node = ray_tpu.remote(_run_node)

    refs: dict[Any, Any] = {}

    def materialize(key, stack=()):
        if key in refs:
            return refs[key]
        if key in stack:
            raise ValueError(f"cycle in dask graph at {key!r}")
        expr = dsk[key]
        deps: set = set()
        _find_keys(expr, dsk, deps)
        dep_keys = sorted(deps, key=repr)
        dep_refs = [
            materialize(d, stack + (key,)) for d in dep_keys
        ]
        if not _istask(expr) and not isinstance(expr, list):
            # Alias (key -> key) or literal: no task needed.
            if dep_keys:
                refs[key] = dep_refs[0]
            else:
                refs[key] = ray_tpu.put(expr)
            return refs[key]
        refs[key] = run_node.remote(expr, dep_keys, *dep_refs)
        return refs[key]

    def resolve(spec):
        if isinstance(spec, list):
            return [resolve(s) for s in spec]
        return ray_tpu.get(materialize(spec))

    return resolve(keys)
