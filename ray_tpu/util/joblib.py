"""joblib backend: scikit-learn-style `Parallel` fan-out over the
cluster (reference: python/ray/util/joblib/ — register_ray registers a
ray backend so `with parallel_backend("ray"):` runs joblib workloads on
the cluster).

Usage::

    import joblib
    from ray_tpu.util.joblib import register_ray_tpu

    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu"):
        joblib.Parallel()(joblib.delayed(f)(x) for x in data)
"""

from __future__ import annotations

from joblib._parallel_backends import ParallelBackendBase


class _Result:
    """joblib future shim over an ObjectRef: task errors surface here,
    at retrieval (this backend has supports_retrieve_callback=False, so
    joblib's completion callback is dispatch bookkeeping only)."""

    def __init__(self, ref, on_done=None):
        self._ref = ref
        self._on_done = on_done

    def get(self, timeout=None):
        import ray_tpu
        from ray_tpu.exceptions import GetTimeoutError

        try:
            out = ray_tpu.get(self._ref, timeout=timeout)
        except GetTimeoutError:
            # Still running: keep it in the backend's inflight set so a
            # following abort_everything can cancel it.
            raise
        except Exception:
            self._done()
            raise
        self._done()
        return out

    def _done(self):
        if self._on_done is not None:
            self._on_done()
            self._on_done = None


class RayTpuBackend(ParallelBackendBase):
    """Each joblib batch becomes one cluster task."""

    supports_timeout = True

    def __init__(self, **kwargs):
        # joblib batches callables itself; nested parallelism inside a
        # worker falls back to sequential/threading (nesting_level must
        # reach the base class or get_nested_backend computes None + 1).
        kwargs.setdefault("nesting_level", 0)
        super().__init__(**kwargs)
        self._task = None
        # Refs still outstanding (pruned on completion so an abort near
        # the end of a long run cancels only live batches).
        self._inflight: set = set()

    def effective_n_jobs(self, n_jobs):
        import ray_tpu

        if n_jobs == 0:
            raise ValueError("n_jobs == 0 has no meaning")
        total_cpus = int(ray_tpu.cluster_resources().get("CPU", 1))
        if n_jobs is None:
            return max(total_cpus, 1)
        if n_jobs < 0:  # -1 = all cluster CPUs, -2 = all but one, ...
            return max(total_cpus + 1 + n_jobs, 1)
        return n_jobs

    def configure(self, n_jobs=1, parallel=None, **kwargs):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init()

        @ray_tpu.remote
        def _run_joblib_batch(batch):
            return batch()

        self._task = _run_joblib_batch
        self._inflight.clear()
        self.parallel = parallel
        return self.effective_n_jobs(n_jobs)

    def apply_async(self, func, callback=None):
        ref = self._task.remote(func)
        self._inflight.add(ref)
        result = _Result(ref, on_done=lambda: self._inflight.discard(ref))
        if callback is not None:
            # Without retrieve-callback support the callback is pure
            # dispatch bookkeeping (BatchCompletionCallBack.__call__ →
            # _dispatch_new) and must fire on success AND failure —
            # errors surface later via get() in ordered retrieval, so
            # the waiter swallows them (no spurious thread tracebacks).
            import threading

            def wait():
                try:
                    result.get()
                # tpulint: allow(broad-except reason=joblib surfaces task errors at ordered retrieval via get(); the waiter only drives dispatch bookkeeping and a traceback here would be a duplicate)
                except Exception:  # noqa: BLE001 - re-raised at retrieval
                    pass
                finally:
                    callback(result)

            threading.Thread(target=wait, daemon=True).start()
        return result

    def submit(self, func, callback=None):
        # joblib >= 1.4 name for apply_async.
        return self.apply_async(func, callback)

    def abort_everything(self, ensure_ready=True):
        # Best-effort cancel of every outstanding batch (queued batches
        # fail fast; running ones are force-killed and their workers
        # replaced).
        import ray_tpu

        # Snapshot: completion callbacks discard from the set
        # concurrently (daemon wait threads).
        for ref in list(self._inflight):
            try:
                ray_tpu.cancel(ref)
            # tpulint: allow(broad-except reason=abort is best-effort over a racing inflight set; a ref that finished or was already cancelled needs no action)
            except Exception:  # noqa: BLE001 - already finished etc.
                pass
        self._inflight.clear()
        self._task = None
        if ensure_ready:
            self.configure(n_jobs=self.parallel.n_jobs,
                           parallel=self.parallel)


def register_ray_tpu() -> None:
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("ray_tpu", RayTpuBackend)
