"""Distributed tracing (reference:
python/ray/util/tracing/tracing_helper.py — the global switch
`_global_is_tracing_enabled` :88, remote-call wrapping + context
injection into task metadata `_start_span` :411). TPU twist: spans ride
the existing task-event pipeline to the head (no OpenTelemetry daemon),
and `jax_profile` hooks the XLA/jax profiler for on-device traces
(xprof), the TPU analogue of the reference's NVTX/torch-profiler hooks
(compiled_dag_node.py:207ff).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
import uuid

_enabled = False
# (trace_id, span_id) of the span this code runs under.
_current: contextvars.ContextVar[tuple[str, str] | None] = (
    contextvars.ContextVar("ray_tpu_trace", default=None)
)
# Driver-thread spans: .remote() captures context on the CALLER's thread
# before hopping to the runtime loop, so span() records here too
# (contextvars do not cross run_coroutine_threadsafe).
_tl = threading.local()


def enable_tracing() -> None:
    """Turn on span collection for this process's submits (workers
    inherit per-task context through the task spec)."""
    global _enabled
    _enabled = True


def disable_tracing() -> None:
    global _enabled
    _enabled = False


def is_tracing_enabled() -> bool:
    from ray_tpu._private import config

    return _enabled or config.get("TRACE")


def current_context() -> tuple[str, str] | None:
    return _current.get()


def active_context() -> tuple[str, str] | None:
    """Public view of the active span — the contextvar when set, else
    this thread's scope (see _active). What serve's request path ships
    across the handle→replica hop so spans parent correctly."""
    return _active()


def _active() -> tuple[str, str] | None:
    """Current span: the contextvar when set, else this thread's scope
    (span() on driver threads; the worker sets it per executor thread via
    thread_trace before running a sync task, so concurrent actor tasks
    each see their OWN span — no shared process-wide slot)."""
    cur = _current.get()
    if cur is not None:
        return cur
    return getattr(_tl, "cur", None)


@contextlib.contextmanager
def thread_trace(ctx: tuple[str, str] | None):
    """Install `ctx` as this THREAD's active span. Used by the worker to
    carry a task's trace context onto the executor thread that runs its
    sync function (contextvars do not cross run_in_executor); keyed to
    the thread, so interleaved finishes of concurrent traced tasks can't
    restore each other's context."""
    prev = getattr(_tl, "cur", None)
    _tl.cur = ctx
    try:
        yield
    finally:
        _tl.cur = prev


def make_trace_ctx(name: str) -> dict | None:
    """Context dict injected into an outgoing task spec (None when
    tracing is off). An inherited active span counts as enabled, so
    workers propagate traces without flipping their own switch."""
    cur = _active()
    if not is_tracing_enabled() and cur is None:
        return None
    trace_id = cur[0] if cur else uuid.uuid4().hex[:16]
    return {
        "trace_id": trace_id,
        "parent_id": cur[1] if cur else "",
        "name": name,
    }


@contextlib.contextmanager
def activate(trace_ctx: dict | None):
    """Worker side: run the task under its inherited trace context and
    record the execution span. Yields the span_id (or None)."""
    if not trace_ctx:
        yield None
        return
    span_id = uuid.uuid4().hex[:16]
    token = _current.set((trace_ctx["trace_id"], span_id))
    start = time.time()
    try:
        yield span_id
    finally:
        _current.reset(token)
        record_span(
            trace_ctx["trace_id"],
            span_id,
            trace_ctx.get("parent_id", ""),
            trace_ctx.get("name", ""),
            start,
            time.time() - start,
        )


@contextlib.contextmanager
def span(name: str):
    """User-level span (works in drivers and inside tasks)."""
    if not is_tracing_enabled():
        yield
        return
    cur = _active()
    trace_id = cur[0] if cur else uuid.uuid4().hex[:16]
    span_id = uuid.uuid4().hex[:16]
    token = _current.set((trace_id, span_id))
    prev_tl = getattr(_tl, "cur", None)
    _tl.cur = (trace_id, span_id)
    start = time.time()
    try:
        yield
    finally:
        _current.reset(token)
        _tl.cur = prev_tl
        record_span(
            trace_id, span_id, cur[1] if cur else "", name, start,
            time.time() - start,
        )


@contextlib.contextmanager
def trace_scope(ctx: tuple[str, str] | None):
    """Install ``ctx`` as the active trace context for the body without
    recording a span of its own (the caller records one with explicit
    ids via record_span). Contextvar-based, so it is async-safe: set
    inside a coroutine it propagates through that task's awaits and
    cannot leak into concurrent tasks. A None ctx is a no-op."""
    if ctx is None:
        yield
        return
    token = _current.set(ctx)
    try:
        yield
    finally:
        _current.reset(token)


@contextlib.contextmanager
def linked_span(name: str, parent: tuple[str, str] | None = None, **attrs):
    """Measure the body as a span parented under ``parent`` (or the
    active context), installing itself as active so nested spans chain.
    Ungated like emit_span — the serve request path calls it only when
    serve telemetry is on AND an upstream trace context exists, so the
    gating lives at the ingress, not here. Yields the span's
    (trace_id, span_id) so callers can ship it across process hops."""
    cur = parent if parent is not None else _active()
    trace_id = cur[0] if cur else uuid.uuid4().hex[:16]
    span_id = uuid.uuid4().hex[:16]
    token = _current.set((trace_id, span_id))
    start = time.time()
    try:
        yield (trace_id, span_id)
    finally:
        _current.reset(token)
        record_span(
            trace_id, span_id, cur[1] if cur else "", name, start,
            time.time() - start, **attrs,
        )


def record_span(trace_id, span_id, parent_id, name, start, dur, **attrs):
    """Spans ride the task-event buffer (flushed to the head like any
    task state transition, core_worker._flush_events_loop). Extra
    keyword attributes (bytes moved, phase breakdowns, train job) travel
    on the event and surface in the timeline's args."""
    try:
        import ray_tpu.api as api

        core = api._runtime.core
    # tpulint: allow(broad-except reason=span recording must never fail the traced operation; without a runtime there is no event pipeline to record into, so dropping is the contract)
    except Exception:  # noqa: BLE001 - no runtime, drop the span
        return
    if core is None:
        return
    core.record_task_event(
        {"task_id": f"span:{span_id}", "name": name},
        "SPAN",
        trace_id=trace_id,
        span_id=span_id,
        parent_id=parent_id,
        ts=start,
        dur=dur,
        **attrs,
    )


def emit_span(name: str, start: float, dur: float, **attrs) -> None:
    """Record an externally measured, already-completed span, linked
    under the active trace context when one exists (fresh trace
    otherwise). Used by the collective flight recorder and train step
    telemetry; NOT gated on enable_tracing — these coarse spans are what
    make `ray_tpu timeline` show collective ops and step phases without
    a tracing opt-in, and recording one is an in-memory list append."""
    cur = _active()
    trace_id = cur[0] if cur else uuid.uuid4().hex[:16]
    span_id = uuid.uuid4().hex[:16]
    record_span(
        trace_id, span_id, cur[1] if cur else "", name, start, dur, **attrs
    )


async def carry_context(coro, ctx: tuple[str, str]):
    """Await `coro` with `ctx` installed as its trace context. The
    collective dispatch layer hops from the caller's thread onto the
    runtime loop (run_coroutine_threadsafe does not propagate
    contextvars), so it captures the caller's active span and re-installs
    it inside the coroutine — spans the op emits (flight recorder) then
    parent under the task that issued the collective. Each asyncio task
    runs in its own Context copy, so the set/reset cannot leak into
    concurrent tasks."""
    token = _current.set(ctx)
    try:
        return await coro
    finally:
        _current.reset(token)


def get_trace_events(limit: int = 2000) -> list[dict]:
    """All spans the head has collected (driver-side query). The SPAN
    filter runs on the head BEFORE `limit` is applied, so busy task
    traffic cannot evict spans from the reply."""
    import ray_tpu.api as api

    rt = api._runtime
    reply = rt.run(
        rt.core.head.call(
            "list_task_events", limit=limit, raw=True, state="SPAN"
        )
    )
    return [e for e in reply["events"] if e.get("state") == "SPAN"]


class ProfileCapture:
    """Handle yielded by jax_profile: `path` resolves to the session
    directory the profiler wrote (``<log_dir>/plugins/profile/<run>``)
    after the context exits, None when nothing was written."""

    __slots__ = ("log_dir", "path")

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self.path: str | None = None


def _resolve_capture_path(log_dir: str) -> str | None:
    """Newest run directory under <log_dir>/plugins/profile/ — where
    jax.profiler.stop_trace lands the xplane.pb + tool files."""
    root = os.path.join(log_dir, "plugins", "profile")
    try:
        runs = [
            os.path.join(root, d)
            for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d))
        ]
    except OSError:
        return None
    if not runs:
        return None
    return max(runs, key=os.path.getmtime)


@contextlib.contextmanager
def jax_profile(log_dir: str | None = None):
    """On-device profiling via the jax/XLA profiler (xprof): wraps
    jax.profiler.start_trace/stop_trace. View with tensorboard or
    xprof. The TPU-native replacement for the reference's NVTX ranges.

    Yields a :class:`ProfileCapture` whose ``path`` is filled in after
    the body exits (the run directory holding the ``*.xplane.pb``), and
    emits a ``profile:capture`` span so captures are discoverable from
    ``timeline()``. ``log_dir`` defaults to ``RAY_TPU_PROFILE_DIR``
    (falling back to ``<tmpdir>/ray_tpu_profile``) and is created if
    missing."""
    import tempfile

    import jax

    from ray_tpu._private import config

    if log_dir is None:
        log_dir = config.get("PROFILE_DIR") or os.path.join(
            tempfile.gettempdir(), "ray_tpu_profile"
        )
    os.makedirs(log_dir, exist_ok=True)
    cap = ProfileCapture(log_dir)
    start = time.time()
    jax.profiler.start_trace(log_dir)
    try:
        yield cap
    finally:
        jax.profiler.stop_trace()
        cap.path = _resolve_capture_path(log_dir)
        emit_span(
            "profile:capture",
            start,
            time.time() - start,
            path=cap.path or log_dir,
        )
