"""ray_tpu.util: state API, metrics, actor pool, queue, and friends
(reference: python/ray/util/)."""
