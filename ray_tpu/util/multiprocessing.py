"""multiprocessing.Pool shim over cluster tasks.

Reference: python/ray/util/multiprocessing/pool.py — drop-in Pool whose
workers are cluster processes, so `Pool.map` scales past one host.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable

import ray_tpu


class AsyncResult:
    def __init__(self, refs: list, single: bool, on_consumed=None):
        self._refs = refs
        self._single = single
        self._on_consumed = on_consumed

    def _consumed(self):
        if self._on_consumed is not None:
            self._on_consumed(self._refs)
            self._on_consumed = None

    def get(self, timeout: float | None = None):
        from ray_tpu.exceptions import GetTimeoutError

        try:
            results = ray_tpu.get(self._refs, timeout=timeout)
        except GetTimeoutError:
            raise  # still in flight: keep the refs tracked
        except Exception:
            self._consumed()  # terminal task error: don't pin the refs
            raise
        self._consumed()
        return results[0] if self._single else results

    def wait(self, timeout: float | None = None):
        ray_tpu.wait(
            self._refs, num_returns=len(self._refs), timeout=timeout
        )

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(
            self._refs, num_returns=len(self._refs), timeout=0
        )
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            # stdlib contract: raises rather than conflating "pending"
            # with "failed".
            raise ValueError("result is not ready")
        try:
            self.get(timeout=0)
            return True
        # tpulint: allow(broad-except reason=stdlib AsyncResult.successful() contract: ANY task error means False; the error itself is re-raised by get())
        except Exception:  # noqa: BLE001
            return False


class Pool:
    """Chunked task fan-out. `processes` bounds in-flight chunks on the
    lazy paths (map/starmap/imap*); the *_async paths submit everything
    up front since they must return immediately."""

    def __init__(self, processes: int | None = None):
        self._processes = processes or 8
        self._run_chunk = ray_tpu.remote(_run_chunk)
        self._closed = False
        self._terminated = False
        # Refs handed out via *_async: join() after close() must block on
        # them. Consumed results are pruned so the pool doesn't pin every
        # historical result in the object store.
        self._outstanding: list = []

    def _drop_refs(self, refs: list):
        ids = {id(r) for r in refs}
        self._outstanding = [
            r for r in self._outstanding if id(r) not in ids
        ]

    def _windowed(self, fn, chunks, star: bool):
        """Yield chunk results in order with ≤ `processes` in flight."""
        chunks = list(chunks)
        inflight: list = []
        next_submit = 0
        for i in range(len(chunks)):
            while next_submit < len(chunks) and (
                len(inflight) < self._processes
            ):
                inflight.append(
                    self._run_chunk.remote(fn, chunks[next_submit], star)
                )
                next_submit += 1
            yield ray_tpu.get(inflight.pop(0))

    def _chunks(self, iterable: Iterable, chunksize: int | None):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        for i in range(0, len(items), chunksize):
            yield items[i : i + chunksize]

    def map(self, fn: Callable, iterable: Iterable, chunksize=None) -> list:
        self._check_open()
        return list(
            itertools.chain.from_iterable(
                self._windowed(fn, self._chunks(iterable, chunksize), False)
            )
        )

    def map_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        self._check_open()
        refs = [
            self._run_chunk.remote(fn, chunk, False)
            for chunk in self._chunks(iterable, chunksize)
        ]
        self._outstanding.extend(refs)
        return _FlattenResult(refs, on_consumed=self._drop_refs)

    def starmap(self, fn, iterable, chunksize=None) -> list:
        self._check_open()
        return list(
            itertools.chain.from_iterable(
                self._windowed(fn, self._chunks(iterable, chunksize), True)
            )
        )

    def apply(self, fn, args=(), kwds=None) -> Any:
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn, args=(), kwds=None) -> AsyncResult:
        self._check_open()
        task = ray_tpu.remote(fn)
        ref = task.remote(*args, **(kwds or {}))
        self._outstanding.append(ref)
        return AsyncResult([ref], single=True, on_consumed=self._drop_refs)

    def imap(self, fn, iterable, chunksize=1):
        self._check_open()
        for chunk_result in self._windowed(
            fn, self._chunks(iterable, chunksize), False
        ):
            yield from chunk_result

    def imap_unordered(self, fn, iterable, chunksize=1):
        self._check_open()
        chunks = list(self._chunks(iterable, chunksize))
        inflight: list = []
        next_submit = 0
        while next_submit < len(chunks) or inflight:
            while next_submit < len(chunks) and (
                len(inflight) < self._processes
            ):
                inflight.append(
                    self._run_chunk.remote(fn, chunks[next_submit], False)
                )
                next_submit += 1
            ready, inflight = ray_tpu.wait(inflight, num_returns=1)
            for ref in ready:  # wait may report more than num_returns
                yield from ray_tpu.get(ref)

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True
        self._terminated = True  # join() must NOT wait for abandoned work

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still open")
        # stdlib contract: close()+join() waits for outstanding work;
        # terminate()+join() returns without completing it.
        if self._outstanding and not self._terminated:
            ray_tpu.wait(
                self._outstanding,
                num_returns=len(self._outstanding),
                timeout=None,
            )
        self._outstanding = []

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool not running")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()


class _FlattenResult(AsyncResult):
    def __init__(self, refs: list, on_consumed=None):
        super().__init__(refs, single=False, on_consumed=on_consumed)

    def get(self, timeout: float | None = None):
        out = list(
            itertools.chain.from_iterable(
                ray_tpu.get(self._refs, timeout=timeout)
            )
        )
        self._consumed()
        return out


def _run_chunk(fn: Callable, chunk: list, star: bool) -> list:
    if star:
        return [fn(*item) for item in chunk]
    return [fn(item) for item in chunk]
