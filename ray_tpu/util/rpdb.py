"""Remote pdb: debug live or crashed tasks over a TCP socket.

Reference: python/ray/util/rpdb.py — ``_RemotePdb`` serves a pdb
session on a listening socket (``ray debug`` / telnet attaches), with
``set_trace()`` for live breakpoints and post-mortem activation on
task failure behind RAY_DEBUG_POST_MORTEM. Same shape here:

- ``ray_tpu.util.rpdb.set_trace()`` inside a task/actor method opens a
  loopback socket, announces the address on the worker's stdout (which
  the log pipeline streams to the driver), and blocks until a client
  attaches (``nc HOST PORT`` — plain pdb protocol, no special client).
- With ``RAY_TPU_POST_MORTEM=1``, a task that raises drops into the
  debugger at the failure frame BEFORE the error travels back to the
  owner; attach, inspect, ``c``/``q`` to release the task.

``RAY_TPU_RPDB_PORT`` pins the listening port (else an ephemeral one);
``RAY_TPU_RPDB_HOST`` the bind host (loopback by default — same
no-auth caveat as the node agent).
"""

from __future__ import annotations

import logging
import os
import pdb
import socket
import sys

logger = logging.getLogger("ray_tpu.rpdb")


class _SocketFile:
    """File-ish adapter for pdb's stdin/stdout over one connection."""

    def __init__(self, conn: socket.socket):
        self._conn = conn
        self._rfile = conn.makefile("r", encoding="utf-8", newline="\n")

    def readline(self):
        line = self._rfile.readline()
        # telnet sends \r\n; pdb wants bare commands.
        return line.replace("\r\n", "\n").replace("\r", "\n")

    def write(self, data: str):
        try:
            self._conn.sendall(data.encode())
        except OSError:
            pass

    def flush(self):
        pass

    def close(self):
        try:
            self._rfile.close()
            self._conn.close()
        except OSError:
            pass

    @property
    def encoding(self):
        return "utf-8"


class RemotePdb(pdb.Pdb):
    """pdb bound to an accepted TCP connection instead of the tty."""

    def __init__(self, host: str | None = None, port: int | None = None):
        # tpulint: allow(TPU703 reason=the remote debugger must come up even when config machinery is the thing being debugged — env-only by design)
        host = host or os.environ.get("RAY_TPU_RPDB_HOST", "127.0.0.1")
        if port is None:
            # tpulint: allow(TPU703 reason=the remote debugger must come up even when config machinery is the thing being debugged — env-only by design)
            port = int(os.environ.get("RAY_TPU_RPDB_PORT", "0"))
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(1)
        self.addr = self._listener.getsockname()[:2]
        # The announcement travels the worker-log pipeline to the
        # driver (reference: _cry() to stderr + the debugger poll loop).
        print(
            f"RAY_TPU_RPDB: waiting for debugger on "
            f"{self.addr[0]}:{self.addr[1]} — attach with "
            f"`nc {self.addr[0]} {self.addr[1]}` (pid={os.getpid()})",
            flush=True,
        )
        conn, _ = self._listener.accept()
        self._sock_file = _SocketFile(conn)
        super().__init__(
            stdin=self._sock_file, stdout=self._sock_file
        )
        self.use_rawinput = False
        self.prompt = "(ray_tpu-pdb) "

    def _close(self):
        self._sock_file.close()
        try:
            self._listener.close()
        except OSError:
            pass

    # Release the socket when the session ends, however it ends.
    def do_continue(self, arg):
        result = super().do_continue(arg)
        self._close()
        return result

    do_c = do_cont = do_continue

    def do_quit(self, arg):
        result = super().do_quit(arg)
        self._close()
        return result

    do_q = do_exit = do_quit

    def do_EOF(self, arg):
        # Abrupt client disconnect (nc killed, network drop) lands
        # here: release the sockets or a pinned RAY_TPU_RPDB_PORT stays
        # bound (EADDRINUSE) for every later session in this worker.
        try:
            return super().do_EOF(arg)
        finally:
            self._close()


def set_trace(host: str | None = None, port: int | None = None):
    """Breakpoint inside a remote task/actor: blocks the task until a
    client attaches and continues."""
    debugger = RemotePdb(host=host, port=port)
    debugger.set_trace(sys._getframe().f_back)


def post_mortem(tb=None, host: str | None = None, port: int | None = None):
    """Debug a crashed frame; used by the worker's failure path when
    RAY_TPU_POST_MORTEM is set, callable directly too."""
    if tb is None:
        tb = sys.exc_info()[2]
    if tb is None:
        raise ValueError("no traceback to debug")
    debugger = RemotePdb(host=host, port=port)
    try:
        debugger.reset()
        debugger.interaction(None, tb)
    finally:
        debugger._close()


def _maybe_post_mortem(tb=None) -> bool:
    """Worker hook: drop into the debugger if post-mortem is enabled.
    Returns True if a session ran."""
    # tpulint: allow(TPU703 reason=the remote debugger must come up even when config machinery is the thing being debugged — env-only by design)
    if os.environ.get("RAY_TPU_POST_MORTEM", "") in ("", "0", "false"):
        return False
    try:
        post_mortem(tb)
        return True
    except Exception:  # noqa: BLE001 - debugging must not mask the error
        logger.warning(
            "post-mortem debugger failed to attach", exc_info=True
        )
        return False
