"""Distributed FIFO queue backed by an actor.

Reference: python/ray/util/queue.py — Queue wraps an asyncio.Queue inside
a _QueueActor; put/get work from any process holding the handle.
"""

from __future__ import annotations

import asyncio
import queue as _stdlib_queue
from typing import Any

import ray_tpu


class Empty(_stdlib_queue.Empty):
    """Subclasses queue.Empty so `except queue.Empty` keeps working."""


class Full(_stdlib_queue.Full):
    """Subclasses queue.Full so `except queue.Full` keeps working."""


class _QueueActor:
    def __init__(self, maxsize: int):
        self.q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)

    async def put(self, item, timeout: float | None = None):
        if timeout is None:
            await self.q.put(item)
            return True
        try:
            await asyncio.wait_for(self.q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: float | None = None):
        if timeout is None:
            return (True, await self.q.get())
        try:
            return (True, await asyncio.wait_for(self.q.get(), timeout))
        except asyncio.TimeoutError:
            return (False, None)

    def put_nowait(self, item) -> bool:
        try:
            self.q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    def get_nowait(self):
        try:
            return (True, self.q.get_nowait())
        except asyncio.QueueEmpty:
            return (False, None)

    def qsize(self) -> int:
        return self.q.qsize()

    def empty(self) -> bool:
        return self.q.empty()

    def full(self) -> bool:
        return self.q.full()


class Queue:
    def __init__(self, maxsize: int = 0, *, actor_options: dict | None = None):
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0.01)
        cls = ray_tpu.remote(_QueueActor)
        self.actor = cls.options(**opts).remote(maxsize)

    def put(self, item: Any, block: bool = True, timeout: float | None = None):
        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item)):
                raise Full()
            return
        ok = ray_tpu.get(self.actor.put.remote(item, timeout))
        if not ok:
            raise Full()

    def get(self, block: bool = True, timeout: float | None = None) -> Any:
        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty()
            return item
        ok, item = ray_tpu.get(self.actor.get.remote(timeout))
        if not ok:
            raise Empty()
        return item

    def put_nowait(self, item: Any):
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def shutdown(self):
        ray_tpu.kill(self.actor)
