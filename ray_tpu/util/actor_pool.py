"""ActorPool: load-balance tasks over a fixed set of actors.

Reference: python/ray/util/actor_pool.py — same API surface
(submit/get_next/get_next_unordered/map/map_unordered/has_next,
push/pop_idle).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator

import ray_tpu


class ActorPool:
    def __init__(self, actors: list):
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._index_to_future: dict[int, Any] = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending: list[tuple[Callable, Any]] = []

    def submit(self, fn: Callable, value: Any) -> None:
        """fn(actor, value) -> ObjectRef; queued if no actor is idle."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = actor
            self._index_to_future[self._next_task_index] = ref
            self._next_task_index += 1
        else:
            self._pending.append((fn, value))

    def _drain_pending(self) -> None:
        while self._pending and self._idle:
            fn, value = self._pending.pop(0)
            self.submit(fn, value)

    def has_next(self) -> bool:
        return bool(self._index_to_future or self._pending)

    def get_next(self, timeout: float | None = None) -> Any:
        """Next result in submission order."""
        if not self.has_next():
            raise StopIteration("no pending results")
        idx = self._next_return_index
        if idx not in self._index_to_future:
            self._drain_pending()
        if idx not in self._index_to_future:
            # The index was consumed by get_next_unordered(); ordered and
            # unordered retrieval cannot be mixed for the same tasks.
            raise RuntimeError(
                f"result #{idx} was already taken (mixed get_next with "
                "get_next_unordered?)"
            )
        ref = self._index_to_future[idx]
        if timeout is not None:
            # Probe readiness without consuming pool state: a timeout
            # must leave the result retrievable and the actor tracked.
            ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=timeout)
            if not ready:
                raise TimeoutError(f"result #{idx} not ready in {timeout}s")
        # Free the actor BEFORE fetching: a task that raised must not
        # wedge the pool (its error re-raises here, but the actor is back
        # in rotation and the index has advanced).
        del self._index_to_future[idx]
        self._next_return_index += 1
        self._idle.append(self._future_to_actor.pop(ref))
        self._drain_pending()
        return ray_tpu.get(ref)

    def get_next_unordered(self, timeout: float | None = None) -> Any:
        """Next result in completion order."""
        if not self.has_next():
            raise StopIteration("no pending results")
        self._drain_pending()
        refs = list(self._index_to_future.values())
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no result within timeout")
        ref = ready[0]
        for idx, r in list(self._index_to_future.items()):
            if r is ref:
                del self._index_to_future[idx]
                break
        self._idle.append(self._future_to_actor.pop(ref))
        self._drain_pending()
        return ray_tpu.get(ref)  # may re-raise the task's error

    def map(self, fn: Callable, values: Iterable) -> Iterator:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable) -> Iterator:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def push(self, actor) -> None:
        self._idle.append(actor)
        self._drain_pending()

    def pop_idle(self):
        return self._idle.pop() if self._idle else None
