"""Scheduling strategies (reference surface:
python/ray/util/scheduling_strategies.py —
PlacementGroupSchedulingStrategy :17, NodeAffinitySchedulingStrategy :43,
NodeLabelSchedulingStrategy :164).

Passed via ``.options(scheduling_strategy=...)`` on tasks and actors.
TPU note: node labels are the reference's mechanism for slice topology
("TPU-<ver>-head", slice names — util/tpu.py:345 _reserve_slice), so
label scheduling is what pins work to a specific slice or host kind."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass
class PlacementGroupSchedulingStrategy:
    """Run on a reserved bundle of a placement group."""

    placement_group: Any
    placement_group_bundle_index: int = 0
    placement_group_capture_child_tasks: bool = False


@dataclass
class NodeAffinitySchedulingStrategy:
    """Pin to a node by id. ``soft=False`` fails when the node cannot
    take the work; ``soft=True`` falls back to normal scheduling."""

    node_id: str
    soft: bool = False


@dataclass
class NodeLabelSchedulingStrategy:
    """Match nodes by label. ``hard`` constraints filter candidate
    nodes (label → value or list of acceptable values); ``soft``
    constraints only raise a matching node's score."""

    hard: dict = field(default_factory=dict)
    soft: dict = field(default_factory=dict)


def to_scheduling_spec(strategy) -> dict | None:
    """Strategy object → wire dict for the lease path (None for the
    default hybrid policy)."""
    if strategy is None or strategy == "DEFAULT":
        return None
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        return {"node_id": strategy.node_id, "soft": strategy.soft}
    if isinstance(strategy, NodeLabelSchedulingStrategy):
        return {
            "labels_hard": dict(strategy.hard),
            "labels_soft": dict(strategy.soft),
        }
    raise TypeError(f"unsupported scheduling strategy: {strategy!r}")


def labels_match(node_labels: dict, constraints: dict) -> bool:
    for key, want in (constraints or {}).items():
        have = node_labels.get(key)
        if isinstance(want, (list, tuple, set)):
            if have not in want:
                return False
        elif have != want:
            return False
    return True
