"""User-defined metrics: Counter / Gauge / Histogram.

Reference: python/ray/util/metrics.py — tagged metrics defined in any
worker, exported cluster-wide. Here each process keeps a registry whose
snapshot rides the core worker's event-flush loop to the head
(reference pipeline: stats/metric.h → OpenTelemetryMetricRecorder →
per-node MetricsAgent → Prometheus scrape,
python/ray/_private/metrics_agent.py:628); `cluster_metrics()` merges
worker snapshots and `prometheus_text()` renders the exposition format a
scraper would consume.
"""

from __future__ import annotations

import bisect
import threading
from typing import Sequence

_REGISTRY: dict[str, "_Metric"] = {}
_LOCK = threading.Lock()

DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0
)


def escape_label_value(value) -> str:
    """Prometheus label-value escaping: one hostile value must not be
    able to break out of its quotes or inject exposition lines."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def parse_tag_str(tag_str: str) -> dict[str, str]:
    """Inverse of the snapshot tag rendering (`k="v",k2="v2"`, values
    escaped with escape_label_value)."""
    out: dict[str, str] = {}
    i, n = 0, len(tag_str)
    while i < n:
        eq = tag_str.find('="', i)
        if eq < 0:
            break
        key = tag_str[i:eq]
        j = eq + 2
        buf: list[str] = []
        while j < n:
            c = tag_str[j]
            if c == "\\" and j + 1 < n:
                buf.append({"n": "\n"}.get(tag_str[j + 1], tag_str[j + 1]))
                j += 2
                continue
            if c == '"':
                break
            buf.append(c)
            j += 1
        out[key] = "".join(buf)
        i = j + 2  # past the closing quote and the separating comma
    return out


class _Metric:
    kind = ""

    def __new__(cls, name: str, *args, **kwargs):
        # Re-registration of an existing name with the same kind hands
        # back the live instance (its series survive) instead of
        # silently shadowing it; __init__ then verifies the shape.
        with _LOCK:
            existing = _REGISTRY.get(name)
        if existing is not None and type(existing) is cls:
            return existing
        return object.__new__(cls)

    def __init__(
        self,
        name: str,
        description: str = "",
        tag_keys: Sequence[str] = (),
    ):
        tag_keys = tuple(tag_keys)
        if getattr(self, "_registered", False):
            if tag_keys != self.tag_keys:
                raise ValueError(
                    f"metric {name!r} already registered with tag_keys "
                    f"{self.tag_keys}, cannot re-register with {tag_keys}"
                )
            return
        with _LOCK:
            existing = _REGISTRY.get(name)
            if existing is not None and existing is not self:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{existing.kind}, cannot re-register as {self.kind}"
                )
            self.name = name
            self.description = description
            self.tag_keys = tag_keys
            self._default_tags: dict[str, str] = {}
            # tag-value tuple → value (float for counter/gauge, list for
            # hist)
            self._series: dict[tuple, object] = {}
            self._registered = True
            _REGISTRY[name] = self

    def set_default_tags(self, tags: dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: dict[str, str] | None) -> tuple:
        merged = {**self._default_tags, **(tags or {})}
        unknown = set(merged) - set(self.tag_keys)
        if unknown:
            raise ValueError(
                f"tags {sorted(unknown)} not in tag_keys {self.tag_keys}"
            )
        return tuple(merged.get(k, "") for k in self.tag_keys)

    def value(self, tags: dict[str, str] | None = None, default=None):
        """Read the current value for one tag set (counter/gauge: float;
        histogram: [bucket_counts, sum, count]). In-process observers —
        the collective straggler telemetry's tests, health checks —
        read this instead of round-tripping a snapshot."""
        with _LOCK:
            return self._series.get(self._key(tags), default)


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, tags: dict | None = None):
        if value < 0:
            raise ValueError("counters only increase")
        key = self._key(tags)
        with _LOCK:
            self._series[key] = self._series.get(key, 0.0) + value


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, tags: dict | None = None):
        with _LOCK:
            self._series[self._key(tags)] = float(value)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Sequence[float] = DEFAULT_BUCKETS,
        tag_keys: Sequence[str] = (),
    ):
        boundaries = tuple(sorted(boundaries))
        if (
            getattr(self, "_registered", False)
            and boundaries != self.boundaries
        ):
            raise ValueError(
                f"histogram {name!r} already registered with boundaries "
                f"{self.boundaries}, cannot re-register with {boundaries}"
            )
        super().__init__(name, description, tag_keys)
        self.boundaries = boundaries

    def observe(self, value: float, tags: dict | None = None):
        key = self._key(tags)
        with _LOCK:
            series = self._series.get(key)
            if series is None:
                # bucket counts (len+1 for +Inf), sum, count
                series = [[0] * (len(self.boundaries) + 1), 0.0, 0]
                self._series[key] = series
            idx = bisect.bisect_left(self.boundaries, value)
            series[0][idx] += 1
            series[1] += value
            series[2] += 1


def snapshot() -> dict:
    """Serializable {name: record} for this process's registry."""
    out = {}
    with _LOCK:
        for name, m in _REGISTRY.items():
            kind = m.kind
            series = {}
            for key, val in m._series.items():
                tag_str = ",".join(
                    f'{k}="{escape_label_value(v)}"'
                    for k, v in zip(m.tag_keys, key)
                )
                series[tag_str] = (
                    [list(val[0]), val[1], val[2]]
                    if kind == "histogram"
                    else val
                )
            if series:
                out[name] = {
                    "kind": kind,
                    "description": m.description,
                    "series": series,
                    "boundaries": getattr(m, "boundaries", None),
                }
    return out


def clear_registry():
    """Test helper: zero every metric without deregistering it.

    Live metric objects (module-level singletons like the collective
    flight recorder's) keep recording after a clear; dropping them from
    the registry would orphan them — still counting, never scraped."""
    with _LOCK:
        for m in _REGISTRY.values():
            m._series.clear()


def merge_snapshots(worker_snaps: dict[str, dict]) -> dict:
    """Merge per-worker snapshots: counters/histograms sum, gauges keep
    the per-worker latest under a worker tag."""
    merged: dict[str, dict] = {}
    for worker, snap in worker_snaps.items():
        for name, rec in snap.items():
            m = merged.setdefault(
                name,
                {
                    "kind": rec["kind"],
                    "description": rec["description"],
                    "series": {},
                    "boundaries": rec.get("boundaries"),
                },
            )
            for tag_str, val in rec["series"].items():
                if rec["kind"] == "gauge":
                    wtag = (
                        f'{tag_str},worker="{escape_label_value(worker)}"'
                    ).lstrip(",")
                    m["series"][wtag] = val
                elif rec["kind"] == "counter":
                    m["series"][tag_str] = m["series"].get(tag_str, 0.0) + val
                else:  # histogram
                    cur = m["series"].get(tag_str)
                    if cur is None:
                        m["series"][tag_str] = [
                            list(val[0]), val[1], val[2]
                        ]
                    else:
                        cur[0] = [a + b for a, b in zip(cur[0], val[0])]
                        cur[1] += val[1]
                        cur[2] += val[2]
    return merged


def prometheus_text(merged: dict) -> str:
    """Render merged metrics in Prometheus exposition format."""
    lines = []
    for name, rec in merged.items():
        if rec["description"]:
            # HELP is one line by format: a newline in a description
            # would start a bogus exposition line mid-scrape.
            desc = (
                rec["description"]
                .replace("\\", "\\\\")
                .replace("\n", " ")
            )
            lines.append(f"# HELP {name} {desc}")
        lines.append(f"# TYPE {name} {rec['kind']}")
        for tag_str, val in rec["series"].items():
            braces = f"{{{tag_str}}}" if tag_str else ""
            if rec["kind"] == "histogram":
                counts, total, n = val
                cum = 0
                for bound, c in zip(rec["boundaries"], counts):
                    cum += c
                    sep = "," if tag_str else ""
                    lines.append(
                        f'{name}_bucket{{{tag_str}{sep}le="{bound}"}} {cum}'
                    )
                sep = "," if tag_str else ""
                lines.append(
                    f'{name}_bucket{{{tag_str}{sep}le="+Inf"}} {n}'
                )
                lines.append(f"{name}_sum{braces} {total}")
                lines.append(f"{name}_count{braces} {n}")
            else:
                lines.append(f"{name}{braces} {val}")
    return "\n".join(lines) + "\n"
