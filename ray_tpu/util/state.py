"""State API: inspect live cluster state (reference:
python/ray/util/state/api.py — `ray list tasks/actors/nodes/...` backed by
GCS task events and tables).

All calls query the head service through the driver's core worker.
"""

from __future__ import annotations

import json
from typing import Any

from ray_tpu import api as core_api


def _call_head(method: str, **kw) -> dict:
    rt = core_api._runtime
    if rt.core is None:
        raise RuntimeError("ray_tpu.init() has not been called")

    async def go():
        return await rt.core.head.call(method, **kw)

    return rt.run(go())


def list_worker_logs() -> list[dict]:
    """Every captured worker log across the cluster (reference:
    `ray logs` listing the session log dir via the per-node agents)."""
    rt = core_api._runtime

    async def fetch():
        from ray_tpu._private import rpc as _rpc

        table = await rt.core.head.call("node_table")
        out = []
        for nid, n in table.items():
            # Per-node failures (dead host mid-listing, dial timeout)
            # skip that node — one unreachable node must not break the
            # cluster-wide listing.
            try:
                conn = await _rpc.connect(n["addr"])
                try:
                    reply = await conn.call("list_logs")
                finally:
                    await conn.close()
            except (_rpc.RpcError, OSError):
                continue
            for rec in reply.get("logs", []):
                out.append({**rec, "node_id": nid})
        return out

    return rt.run(fetch())


def read_worker_log(worker_prefix: str, tail_bytes: int = 0) -> str | None:
    """Log content of the first worker matching the prefix — dead
    workers included. None when no node has a matching log."""
    rt = core_api._runtime

    async def fetch():
        from ray_tpu._private import rpc as _rpc

        table = await rt.core.head.call("node_table")
        for n in table.values():
            try:
                conn = await _rpc.connect(n["addr"])
                try:
                    reply = await conn.call(
                        "read_log",
                        worker_id=worker_prefix,
                        offset=-tail_bytes if tail_bytes else 0,
                    )
                finally:
                    await conn.close()
            except (_rpc.RpcError, OSError):
                continue
            if reply.get("ok"):
                data = reply["data"]
                return (
                    data.decode("utf-8", "replace")
                    if isinstance(data, bytes)
                    else data
                )
        return None

    return rt.run(fetch())


def list_nodes() -> list[dict]:
    table = _call_head("node_table")
    return [
        {
            "node_id": nid,
            "addr": n["addr"],
            "resources": n["resources"],
            "available": n["available"],
            "labels": n.get("labels", {}),
            "agent_addr": n.get("agent_addr"),
        }
        for nid, n in table.items()
    ]


def list_actors(state: str | None = None) -> list[dict]:
    actors = _call_head("list_actors")["actors"]
    out = [
        {"actor_id": aid, **info}
        for aid, info in actors.items()
        if state is None or info["state"] == state
    ]
    return out


def list_tasks(limit: int = 1000, state: str | None = None) -> list[dict]:
    # The state filter runs on the head BEFORE limit, so filtered kinds
    # aren't evicted from the newest-N window by other traffic.
    events = _call_head("list_task_events", limit=limit, state=state)[
        "events"
    ]
    if state is not None:
        events = [e for e in events if e.get("state") == state]
    return events


def list_placement_groups() -> list[dict]:
    pgs = _call_head("list_placement_groups")["placement_groups"]
    return [{"pg_id": pid, **pg} for pid, pg in pgs.items()]


def list_objects() -> list[dict]:
    """Objects in this node's shared-memory store."""
    rt = core_api._runtime
    store = rt.core.store
    out = []
    for oid_hex, size in store.list_objects():
        out.append({"object_id": oid_hex, "size_bytes": size})
    return out


def summarize_tasks() -> dict:
    counts: dict[str, int] = {}
    for ev in list_tasks(limit=20000):
        counts[ev.get("state", "?")] = counts.get(ev.get("state", "?"), 0) + 1
    return counts


def cluster_metrics() -> dict:
    """Merged user metrics across all workers."""
    from ray_tpu.util import metrics as m

    workers = _call_head("cluster_metrics")["workers"]
    # Refresh this process's entry from the live registry (its periodic
    # flusher may lag); same key as the flusher uses so the local
    # snapshot replaces — never double-counts — the reported one.
    local = m.snapshot()
    if local:
        workers = {**workers, core_api._runtime.core.addr: local}
    return m.merge_snapshots(workers)


def prometheus_metrics() -> str:
    from ray_tpu.util import metrics as m

    return m.prometheus_text(cluster_metrics())


def train_stats() -> dict:
    """Per-train-job goodput accounting from the head: productive step
    time vs. stalls (inter-step gaps, data wait, checkpointing) and
    elastic restart loss, plus MFU and phase breakdowns. Backs the
    dashboard's /api/train and the `ray_tpu goodput` CLI."""
    return _call_head("train_stats")


def sweep_stats(sweep_id: str | None = None) -> dict:
    """Sweep-engine ledger from the head's journaled ``sweeps`` table:
    per-sweep trial states (gang admission → running → rung-stopped /
    forked / migrated), fork and preemption counters, and each trial's
    live train-job ledger row joined in. Backs the dashboard's
    /api/tune and the `ray_tpu tune` CLI; survives head restart via
    journal replay."""
    return _call_head("sweep_stats", sweep_id=sweep_id)


def serve_stats() -> dict:
    """Per-deployment serve SLO ledger from the head: request/error
    counts, sliding-window TTFT/latency p50/p99, SLO attainment, and
    the burn-rate alert state. Backs the dashboard's /api/serve and the
    `ray_tpu slo` CLI."""
    return _call_head("serve_stats")


def mem_stats() -> dict:
    """Device-memory ledger from the head: per-node current/peak used
    bytes, capacity, headroom alert state, and per-subsystem byte
    attribution, plus per-job peaks. Backs the dashboard's /api/memory
    and the `ray_tpu mem` CLI."""
    return _call_head("mem_stats")


def profile_stats() -> dict:
    """Per-job compiled-program profile from the head: the latest MFU
    decomposition (category shares + dominant gap) and the journaled
    per-signature fingerprints the regression sentinel compares new
    captures against. Backs the dashboard's /api/profile and the
    `ray_tpu profile` CLI."""
    return _call_head("profile_stats")


def profile_capture(steps: int | None = None) -> dict:
    """Ask the head to fan a compiled-program capture request out to
    every rank (collective-channel riders arm their per-step profiler
    hook; reports land in profile_stats after the next
    PROFILE_CAPTURE_STEPS steps)."""
    return _call_head("profile_capture", steps=steps)


def head_stats() -> dict:
    """Head control-plane load stats: telemetry fold-queue depth, shed
    counter, overload alert state, pubsub coalescing counters, and
    journal size/compaction. Backs the dashboard's /api/head and the
    `ray_tpu head` CLI."""
    return _call_head("head_stats")


def list_checkpoints(run: str | None = None) -> dict:
    """In-cluster shard-store checkpoints per run (step, world,
    completeness, bytes, chunk count, min replica count). Backs the
    dashboard's /api/checkpoints and `ray_tpu ckpt ls`."""
    return _call_head("ckpt_list", run=run)


def verify_checkpoints(run: str | None = None) -> dict:
    """Probe every retained checkpoint chunk on its recorded holders;
    reports under-replicated and lost chunks (`ray_tpu ckpt verify`)."""
    return _call_head("ckpt_verify", run=run)


_SPAN_ARG_KEYS = (
    "trace_id", "span_id", "parent_id", "group", "verb", "backend",
    "bytes", "dtype", "bus_bytes_per_s", "train_job", "train_attempt",
    "train_rank", "train_step", "phases", "mfu",
    "comm_exposed_s", "comm_overlapped_s", "degraded_frac",
    # serve request-path spans: the ids/attrs that make one request's
    # span tree reconstructable from the chrome trace
    "app", "deployment", "route", "status", "ttft_s", "request_id",
    "streamed", "items", "tokens", "batch_size", "occupancy",
    "queue_s", "sample_rate",
    # compiled-program profiler spans (profile:step / profile:capture)
    "profile_sig", "profile_shares", "profile_step_s", "profile_steps",
    "profile_dominant", "path",
)


def timeline(path: str | None = None) -> list[dict] | str:
    """Chrome-trace export of task execution spans plus SPAN events —
    collective ops and train step phases render as slices alongside the
    tasks that issued them (reference: `ray timeline`, powered by
    GcsTaskManager events)."""
    events = _call_head("list_task_events", limit=20000, raw=True)["events"]
    trace = []
    for ev in events:
        if ev.get("state") == "SPAN" and "dur" in ev:
            trace.append(
                {
                    "ph": "X",
                    "name": ev.get("name") or "span",
                    "ts": ev["ts"] * 1e6,
                    "dur": ev["dur"] * 1e6,
                    "pid": ev.get("worker", "?"),
                    # Separate track per worker so span slices don't
                    # overlap the task slices they ran inside.
                    "tid": "spans",
                    "args": {
                        k: ev[k] for k in _SPAN_ARG_KEYS if k in ev
                    },
                }
            )
            continue
        if ev.get("state") != "RUNNING" or "dur" not in ev:
            continue
        trace.append(
            {
                "ph": "X",
                "name": ev.get("name") or ev.get("task_id", "")[:8],
                "ts": ev["ts"] * 1e6,
                "dur": ev["dur"] * 1e6,
                "pid": ev.get("worker", "?"),
                "tid": 0,
                "args": {"task_id": ev.get("task_id")},
            }
        )
    if path is None:
        return trace
    with open(path, "w") as f:
        json.dump(trace, f)
    return path
