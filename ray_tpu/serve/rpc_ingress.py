"""Native-rpc ingress: the framework-protocol alternative to the HTTP
proxy (reference: python/ray/serve/_private/proxy.py gRPCProxy :534 —
typed non-HTTP ingress alongside HTTPProxy; here the wire is the
runtime's own rpc framing, so in-cluster callers skip HTTP entirely).

Server: deploy ``RpcIngressActor`` as an actor and call ``start``::

    ingress = ray_tpu.remote(serve.RpcIngressActor).remote()
    addr = ray_tpu.get(ingress.start.remote())

It serves ``serve_request`` rpcs that name the target deployment
directly (like a gRPC service routes by method, not by URL path).
Client: :func:`rpc_request` from any process with a runtime."""

from __future__ import annotations

from ray_tpu.serve.handle import DeploymentHandle


class RpcIngressActor:
    """Deploy with ``ray_tpu.remote(RpcIngressActor).remote()`` then
    ``await``/get ``start.remote()`` for the serving address."""

    def __init__(self):
        self._handles: dict[tuple, DeploymentHandle] = {}
        self._server = None
        self._addr: str | None = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        from ray_tpu._private import rpc

        self._server = rpc.Server(self._on_rpc)
        p = await self._server.start(host, port)
        self._addr = f"{host}:{p}"
        return self._addr

    def get_addr(self) -> str | None:
        return self._addr

    async def _on_rpc(self, method: str, kw: dict, conn):
        from ray_tpu._private import rpc

        if method != "serve_request":
            raise rpc.RpcError(f"rpc ingress: unknown method {method!r}")
        deployment = kw["deployment"]
        app = kw.get("app", "default")
        call_method = kw.get("call_method", "__call__")
        key = (app, deployment, call_method)
        handle = self._handles.get(key)
        if handle is None:
            handle = DeploymentHandle(
                deployment, app, method_name=call_method
            )
            self._handles[key] = handle
        try:
            result = await handle.remote(
                *kw.get("args", ()), **kw.get("kwargs", {})
            )
            return {"ok": True, "result": result}
        # tpulint: allow(broad-except reason=handler failure is encoded into the RPC reply envelope and travels to the caller typed — not swallowed)
        except Exception as e:  # noqa: BLE001 - travels to the caller
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    async def shutdown(self) -> bool:
        if self._server is not None:
            await self._server.stop()
        return True


def rpc_request(
    addr: str,
    deployment: str,
    *args,
    app: str = "default",
    method: str = "__call__",
    timeout: float | None = 60.0,
    **kwargs,
):
    """Call a deployment through an rpc ingress (sync, driver/task
    side). Raises RuntimeError on a deployment-side error."""
    import ray_tpu.api as api

    rt = api._runtime

    async def call():
        conn = await rt.core._connect(addr)
        # tpulint: allow(TPU701 reason=the ingress is a raw dispatcher — rpc.Server routes serve_request inside _on_rpc itself, deliberately outside the _on_<method> convention)
        return await conn.call(
            "serve_request",
            deployment=deployment,
            app=app,
            call_method=method,
            args=list(args),
            kwargs=kwargs,
        )

    reply = rt.run(call(), timeout=timeout)
    if not reply.get("ok"):
        raise RuntimeError(f"serve rpc ingress: {reply.get('error')}")
    return reply["result"]
