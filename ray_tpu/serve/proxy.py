"""HTTP proxy actor: routes HTTP requests to application ingress handles.

(reference: python/ray/serve/_private/proxy.py HTTPProxy :710 — uvicorn/
starlette there; here a stdlib ThreadingHTTPServer inside the proxy
actor. Handler threads use the sync DeploymentHandle path, which is safe
off the runtime loop.)

Request mapping: the ingress deployment is called with a single dict
argument {"method", "path", "query", "body"} where body is parsed JSON
when the content type (or payload) is JSON, else raw bytes. A str/bytes
return value is sent verbatim; anything else is JSON-encoded.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import ray_tpu
from ray_tpu.serve.handle import CONTROLLER_NAME, DeploymentHandle

_ROUTE_TTL_S = 2.0


class ProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._routes: dict[str, tuple] = {}  # prefix → (app, ingress)
        self._handles: dict[str, DeploymentHandle] = {}
        self._routes_ts = 0.0
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            def _serve(self, body: bytes | None):
                try:
                    status, payload = proxy._dispatch(
                        self.command, self.path, body
                    )
                except Exception as e:  # noqa: BLE001
                    status, payload = 500, str(e).encode()
                self.send_response(status)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):  # noqa: N802 (stdlib API)
                self._serve(None)

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0))
                self._serve(self.rfile.read(n) if n else b"")

            do_PUT = do_POST  # noqa: N815
            do_DELETE = do_GET  # noqa: N815

            def log_message(self, *a):  # silence per-request stderr
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def get_port(self) -> int:
        return self._server.server_address[1]

    def _refresh_routes(self):
        now = time.monotonic()
        if now - self._routes_ts < _ROUTE_TTL_S and self._routes:
            return
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        self._routes = ray_tpu.get(controller.get_route_table.remote())
        self._routes_ts = time.monotonic()

    def _dispatch(self, method: str, path: str, body: bytes | None):
        self._refresh_routes()
        parsed = urllib.parse.urlparse(path)
        route = parsed.path
        match = None
        for prefix in sorted(self._routes, key=len, reverse=True):
            if route == prefix or route.startswith(
                prefix.rstrip("/") + "/"
            ) or prefix == "/":
                match = prefix
                break
        if match is None:
            return 404, b"no route"
        app_name, ingress = self._routes[match]
        handle = self._handles.get(app_name)
        if handle is None or handle.deployment_name != ingress:
            handle = DeploymentHandle(ingress, app_name)
            self._handles[app_name] = handle

        payload: object = body
        if body:
            try:
                payload = json.loads(body)
            except ValueError:
                payload = body
        request = {
            "method": method,
            "path": route,
            "query": dict(urllib.parse.parse_qsl(parsed.query)),
            "body": payload,
        }
        result = handle.remote(request).result(timeout=60)
        if isinstance(result, bytes):
            return 200, result
        if isinstance(result, str):
            return 200, result.encode()
        return 200, json.dumps(result).encode()

    def shutdown(self):
        self._server.shutdown()
        return True
