"""Async HTTP proxy actor: routes HTTP requests to application ingress
handles, with streaming (SSE / chunked) responses.

(reference: python/ray/serve/_private/proxy.py:710 HTTPProxy — a fully
async uvicorn/ASGI proxy there with StreamingResponse support; here a
raw asyncio HTTP/1.1 server running on the worker's runtime event loop,
so request handlers await DeploymentHandle calls natively with no
thread hops.)

Request mapping: the ingress deployment is called with a single dict
argument {"method", "path", "query", "headers", "body"} where body is
parsed JSON when the payload is JSON, else raw bytes. A str/bytes return
value is sent verbatim; anything else is JSON-encoded.

Streaming: a request opts in via `Accept: text/event-stream`, a
`?stream=1` query parameter, or a JSON body containing `"stream": true`.
The proxy then makes a streaming handle call (replica generators stream
through the core's ObjectRefGenerator path) and writes each yielded item
as a Server-Sent-Events `data:` frame over chunked transfer encoding,
ending with `data: [DONE]` (the OpenAI wire convention).
"""

from __future__ import annotations

import asyncio
import json
import logging
import urllib.parse

logger = logging.getLogger("ray_tpu.serve")

from ray_tpu.exceptions import NoReplicaAvailableError
from ray_tpu.serve.handle import (
    DeploymentHandle,
    DeploymentStreamResponse,
)

_REQUEST_TIMEOUT_S = 60.0
_BODY_READ_TIMEOUT_S = 30.0
_MAX_BODY = 64 * 1024 * 1024
_MAX_INFLIGHT = 256
_HEX = frozenset(b"0123456789abcdefABCDEF")

_REASONS = {
    200: "OK",
    400: "Bad Request",
    404: "Not Found",
    408: "Timeout",
    413: "Payload Too Large",
    500: "Internal",
    503: "Service Unavailable",
}


def _sse_frame(item) -> bytes:
    """One SSE event per yielded item; multi-line payloads get one
    `data:` line each per the SSE spec."""
    if isinstance(item, bytes):
        payload = item.decode("utf-8", "replace")
    elif isinstance(item, str):
        payload = item
    else:
        payload = json.dumps(item)
    lines = payload.split("\n")
    return ("".join(f"data: {ln}\n" for ln in lines) + "\n").encode()


def _chunk(data: bytes) -> bytes:
    return b"%x\r\n%s\r\n" % (len(data), data)


class _BodyTooLarge(Exception):
    pass


class ProxyActor:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        max_body_bytes: int = _MAX_BODY,
        max_inflight: int = _MAX_INFLIGHT,
    ):
        # prefix → (app, ingress, request_timeout_s|None)
        from ray_tpu.serve.routes import RouteTablePoller

        self._poller = RouteTablePoller()
        self._routes: dict[str, tuple] = {}
        self._handles: dict[str, DeploymentHandle] = {}
        self._server: asyncio.AbstractServer | None = None
        self._max_body = max_body_bytes
        self._max_inflight = max_inflight
        self._inflight = 0
        self._stats = {"requests": 0, "streams": 0, "errors": 0,
                       "rejected": 0}
        # Actor __init__ runs on the executor thread; the server must
        # live on the runtime loop where handle calls are native.
        from ray_tpu import api as core_api

        asyncio.run_coroutine_threadsafe(
            self._start(host, port), core_api._runtime.loop
        ).result(timeout=30)

    async def _start(self, host: str, port: int):
        self._server = await asyncio.start_server(
            self._handle_conn, host, port
        )

    def get_port(self) -> int:
        return self._server.sockets[0].getsockname()[1]

    def get_stats(self) -> dict:
        return dict(self._stats)

    # ---------------------------------------------------------- routing
    async def _refresh_routes(self, force: bool = False):
        """Poll the controller's route table via the shared poller
        (routes.py — one implementation for the HTTP and gRPC
        ingresses, controller-restart recovery included)."""
        await self._poller.refresh(force)
        self._routes = self._poller.routes

    def _match_route(self, route: str):
        for prefix in sorted(self._routes, key=len, reverse=True):
            if (
                route == prefix
                or route.startswith(prefix.rstrip("/") + "/")
                or prefix == "/"
            ):
                return prefix
        return None

    def _handle_for(self, match: str) -> tuple[DeploymentHandle, float]:
        app_name, ingress, *rest = self._routes[match]
        timeout = (
            rest[0] if rest and rest[0] is not None else _REQUEST_TIMEOUT_S
        )
        handle = self._handles.get(app_name)
        if handle is None or handle.deployment_name != ingress:
            handle = DeploymentHandle(ingress, app_name)
            self._handles[app_name] = handle
        return handle, timeout

    # ------------------------------------------------------- connection
    async def _handle_conn(self, reader, writer):
        try:
            while True:
                keep_alive = await self._handle_one(reader, writer)
                if not keep_alive:
                    break
        except (
            asyncio.IncompleteReadError,
            ConnectionResetError,
            BrokenPipeError,
            TimeoutError,
        ):
            pass
        except Exception:  # noqa: BLE001 - never kill the accept loop
            self._stats["errors"] += 1
            logger.warning(
                "proxy connection handler crashed", exc_info=True
            )
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            # tpulint: allow(broad-except reason=closing a client socket that may already be reset; the request outcome was decided above)
            except Exception:  # noqa: BLE001
                pass

    async def _handle_one(self, reader, writer) -> bool:
        request_line = await reader.readline()
        if not request_line:
            return False
        try:
            method, target, version = (
                request_line.decode("latin-1").rstrip("\r\n").split(" ", 2)
            )
        except ValueError:
            await self._respond(writer, 500, b"malformed request line")
            return False
        headers: dict[str, str] = {}
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            if b":" in line:
                k, v = line.decode("latin-1").split(":", 1)
                k = k.strip().lower()
                v = v.strip()
                if k in headers:
                    # RFC 9110 field-line merging; Cookie is special-cased
                    # per RFC 6265 (semicolon-joined, order preserved).
                    sep = "; " if k == "cookie" else ", "
                    headers[k] = headers[k] + sep + v
                else:
                    headers[k] = v
        # Shed load BEFORE buffering the body: the cap must bound body
        # memory, not just dispatch concurrency, so the slot is claimed
        # here and held through the body read. The unread body forces
        # Connection: close on the 503 (reading it would be the buffering
        # we're avoiding; not reading it would desync keep-alive).
        self._stats["requests"] += 1
        if self._inflight >= self._max_inflight:
            self._stats["rejected"] += 1
            await self._respond(writer, 503, b"proxy at capacity", False)
            return False
        self._inflight += 1
        released = False

        def release() -> None:
            # The slot guards buffered-body memory + dispatch concurrency.
            # Streams release it at dispatch (they buffer nothing after
            # the body), so the decrement must be idempotent.
            nonlocal released
            if not released:
                released = True
                self._inflight -= 1

        try:
            if "chunked" in headers.get("transfer-encoding", "").lower():
                # Decode the chunked body fully; leaving it unread would
                # desync the keep-alive stream (request-smuggling vector
                # behind another HTTP intermediary). The read deadline
                # stops a stalled sender from pinning this slot forever.
                try:
                    body = await asyncio.wait_for(
                        self._read_chunked(reader), _BODY_READ_TIMEOUT_S
                    )
                except _BodyTooLarge:
                    await self._respond(writer, 413, b"body too large")
                    return False
                except (ValueError, asyncio.TimeoutError):
                    await self._respond(writer, 400, b"bad chunked encoding")
                    return False
            else:
                try:
                    n = int(headers.get("content-length", 0) or 0)
                except ValueError:
                    await self._respond(writer, 400, b"bad content-length")
                    return False
                body = b""
                if n:
                    if n > self._max_body:
                        await self._respond(writer, 413, b"body too large")
                        return False
                    try:
                        body = await asyncio.wait_for(
                            reader.readexactly(n), _BODY_READ_TIMEOUT_S
                        )
                    except asyncio.TimeoutError:
                        await self._respond(writer, 408, b"body read timeout")
                        return False
            keep_alive = (
                headers.get("connection", "").lower() != "close"
                and version != "HTTP/1.0"
            )
            return await self._dispatch(
                writer, method, target, headers, body, keep_alive, release
            )
        finally:
            release()

    async def _dispatch(
        self, writer, method, target, headers, body, keep_alive, release
    ) -> bool:
        # Everything below must produce an HTTP response, never a bare
        # connection drop (streaming manages its own error framing).
        # Request-path telemetry: mint (or adopt from traceparent /
        # x-request-id) a trace context at ingress; everything awaited
        # inside the `with tel:` scope — handle dispatch, replica,
        # engine — parents its spans under the serve:ingress root.
        from ray_tpu.serve import telemetry as stel

        tel = stel.begin_request(headers)
        app_name = dep_name = route = ""
        with tel:
            try:
                await self._refresh_routes()
                parsed = urllib.parse.urlparse(target)
                match = self._match_route(parsed.path)
                if match is None:
                    # A just-deployed app may not be in the cached table
                    # yet.
                    await self._refresh_routes(force=True)
                    match = self._match_route(parsed.path)
                if match is None:
                    # Unmatched requests never reach a deployment: no
                    # SLO sample, no span (an unbounded scan of bogus
                    # paths must not pollute the ledger).
                    await self._respond(writer, 404, b"no route", keep_alive)
                    return keep_alive

                query = dict(urllib.parse.parse_qsl(parsed.query))
                payload: object = body
                if body:
                    try:
                        payload = json.loads(body)
                    except ValueError:
                        payload = body
                request = {
                    "method": method,
                    "path": parsed.path,
                    "query": query,
                    "headers": headers,
                    "body": payload,
                }
                want_stream = (
                    "text/event-stream" in headers.get("accept", "")
                    or query.get("stream", "").lower() in ("1", "true")
                    or (isinstance(payload, dict)
                        and bool(payload.get("stream")))
                )
                handle, timeout_s = self._handle_for(match)
                app_name = handle.app_name
                dep_name = handle.deployment_name
                route = match
                if want_stream:
                    self._stats["streams"] += 1
                    # A long-lived stream buffers nothing after this
                    # point; holding the slot for its whole duration
                    # would let 256 legitimate SSE clients starve every
                    # unary request.
                    release()
                    info = {"status": 200, "items": 0}
                    ka = await self._respond_stream(
                        writer, handle, request, keep_alive, timeout_s,
                        tel, info,
                    )
                    tel.finish(
                        app_name, dep_name, route, info["status"],
                        streamed=True, items=info["items"],
                    )
                    return ka
                result = await asyncio.wait_for(
                    handle.remote(request), timeout_s
                )
                if isinstance(result, bytes):
                    out = result
                elif isinstance(result, str):
                    out = result.encode()
                else:
                    out = json.dumps(result).encode()
            except asyncio.TimeoutError:
                self._stats["errors"] += 1
                if dep_name:
                    tel.finish(app_name, dep_name, route, 408)
                await self._respond(
                    writer, 408, b"request timed out", keep_alive
                )
                return keep_alive
            except NoReplicaAvailableError as e:
                # Every replica is dead/draining/circuit-open — the
                # ONLY case the proxy answers 503 for a routed request.
                # Retry-After tells well-behaved clients when the
                # breaker window reopens.
                self._stats["errors"] += 1
                if dep_name:
                    tel.finish(app_name, dep_name, route, 503)
                await self._respond(
                    writer, 503, str(e).encode(), keep_alive,
                    extra_headers={
                        "Retry-After":
                            str(max(1, int(e.retry_after_s + 0.999))),
                    },
                )
                return keep_alive
            # tpulint: allow(broad-except reason=the failure is propagated to the client as the 500 body and counted in proxy stats)
            except Exception as e:  # noqa: BLE001 - user/routing error → 500
                self._stats["errors"] += 1
                if dep_name:
                    tel.finish(app_name, dep_name, route, 500)
                await self._respond(writer, 500, str(e).encode(), keep_alive)
                return keep_alive
            tel.finish(app_name, dep_name, route, 200)
        await self._respond(writer, 200, out, keep_alive)
        return keep_alive

    async def _respond(
        self,
        writer,
        status: int,
        payload: bytes,
        keep_alive: bool = False,
        extra_headers: dict | None = None,
    ):
        reason = _REASONS.get(status, "Unknown")
        conn = "keep-alive" if keep_alive else "close"
        extras = "".join(
            f"{k}: {v}\r\n" for k, v in (extra_headers or {}).items()
        )
        writer.write(
            (
                f"HTTP/1.1 {status} {reason}\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"{extras}"
                f"Connection: {conn}\r\n\r\n"
            ).encode()
            + payload
        )
        await writer.drain()

    async def _read_chunked(self, reader) -> bytes:
        """Decode a chunked request body (RFC 9112 §7.1), bounded by the
        proxy body cap; trailer fields are read and discarded."""
        parts: list[bytes] = []
        total = 0
        while True:
            size_line = await reader.readline()
            if not size_line:
                raise ValueError("eof in chunk size")
            token = size_line.split(b";")[0].strip()
            # Strict HEXDIG only (RFC 9112 §7.1): int(x, 16) would also
            # accept '0x10'/'+10'/'1_0', forms another parser in front of
            # us may read differently — the exact desync this decoder is
            # here to prevent.
            if not token or any(c not in _HEX for c in token):
                raise ValueError("bad chunk size")
            size = int(token, 16)
            if size == 0:
                break
            total += size
            if total > self._max_body:
                raise _BodyTooLarge()
            parts.append(await reader.readexactly(size))
            if await reader.readexactly(2) != b"\r\n":
                raise ValueError("missing chunk terminator")
        for _ in range(64):  # trailer section ends at an empty line
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
        else:
            raise ValueError("unterminated trailer section")
        return b"".join(parts)

    async def _respond_stream(
        self,
        writer,
        handle: DeploymentHandle,
        request: dict,
        keep_alive: bool,
        timeout_s: float = _REQUEST_TIMEOUT_S,
        tel=None,
        info: dict | None = None,
    ) -> bool:
        """Stream the handle call as SSE over chunked transfer encoding.
        Headers are written only once the first item (or first error)
        arrives, so pre-stream failures still get a clean HTTP status.
        ``tel``/``info`` (serve telemetry): first_byte() marks TTFT on
        the first frame; item count and effective status land in
        ``info`` for the ingress span."""
        if info is None:
            info = {}
        stream: DeploymentStreamResponse = handle.options(stream=True).remote(
            request
        )
        agen = stream.__aiter__()
        started = False

        def _sse_headers() -> bytes:
            conn = "keep-alive" if keep_alive else "close"
            return (
                "HTTP/1.1 200 OK\r\n"
                "Content-Type: text/event-stream\r\n"
                "Cache-Control: no-cache\r\n"
                "Transfer-Encoding: chunked\r\n"
                f"Connection: {conn}\r\n\r\n"
            ).encode()

        try:
            while True:
                # Per-item deadline: a replica hung before its next yield
                # must not pin this connection (and its router inflight
                # slot) forever.
                try:
                    item = await asyncio.wait_for(
                        agen.__anext__(), timeout_s
                    )
                except StopAsyncIteration:
                    break
                except asyncio.TimeoutError:
                    self._stats["errors"] += 1
                    await agen.aclose()
                    if not started:
                        # Mirror the unary path: a pre-first-item timeout
                        # is a clean 408, not an empty 500.
                        info["status"] = 408
                        await self._respond(
                            writer, 408, b"request timed out", keep_alive
                        )
                        return keep_alive
                    info["status"] = 500
                    err = json.dumps({"error": "stream item timed out"})
                    writer.write(
                        _chunk(f"event: error\ndata: {err}\n\n".encode())
                        + b"0\r\n\r\n"
                    )
                    await writer.drain()
                    return False
                if not started:
                    started = True
                    if tel is not None:
                        tel.first_byte()
                    writer.write(_sse_headers())
                info["items"] = info.get("items", 0) + 1
                writer.write(_chunk(_sse_frame(item)))
                await writer.drain()
            if not started:
                # Empty stream: still a valid SSE response.
                started = True
                writer.write(_sse_headers())
            writer.write(_chunk(b"data: [DONE]\n\n") + b"0\r\n\r\n")
            await writer.drain()
            return keep_alive
        except (ConnectionResetError, BrokenPipeError):
            # Client went away: stop the replica-side generator.
            info["status"] = 499  # nginx convention: client closed
            await agen.aclose()
            return False
        # tpulint: allow(broad-except reason=the failure reaches the client — as a 500/503 before the stream starts, as a terminal SSE error event mid-stream — and is counted in proxy stats)
        except Exception as e:  # noqa: BLE001
            self._stats["errors"] += 1
            await agen.aclose()
            if not started and isinstance(e, NoReplicaAvailableError):
                # Mirror the unary path: pre-stream unavailability is a
                # clean 503 + Retry-After, not an empty 500.
                info["status"] = 503
                await self._respond(
                    writer, 503, str(e).encode(), keep_alive,
                    extra_headers={
                        "Retry-After":
                            str(max(1, int(e.retry_after_s + 0.999))),
                    },
                )
                return keep_alive
            info["status"] = 500
            if not started:
                await self._respond(writer, 500, str(e).encode(), keep_alive)
                return keep_alive
            # Mid-stream failure: emit an SSE error event, then terminate
            # the chunked body so the client sees a clean end.
            err = json.dumps({"error": str(e)})
            writer.write(
                _chunk(f"event: error\ndata: {err}\n\n".encode())
                + b"0\r\n\r\n"
            )
            await writer.drain()
            return False

    async def shutdown(self):
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        return True
