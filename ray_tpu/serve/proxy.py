"""Async HTTP proxy actor: routes HTTP requests to application ingress
handles, with streaming (SSE / chunked) responses.

(reference: python/ray/serve/_private/proxy.py:710 HTTPProxy — a fully
async uvicorn/ASGI proxy there with StreamingResponse support; here a
raw asyncio HTTP/1.1 server running on the worker's runtime event loop,
so request handlers await DeploymentHandle calls natively with no
thread hops.)

Request mapping: the ingress deployment is called with a single dict
argument {"method", "path", "query", "headers", "body"} where body is
parsed JSON when the payload is JSON, else raw bytes. A str/bytes return
value is sent verbatim; anything else is JSON-encoded.

Streaming: a request opts in via `Accept: text/event-stream`, a
`?stream=1` query parameter, or a JSON body containing `"stream": true`.
The proxy then makes a streaming handle call (replica generators stream
through the core's ObjectRefGenerator path) and writes each yielded item
as a Server-Sent-Events `data:` frame over chunked transfer encoding,
ending with `data: [DONE]` (the OpenAI wire convention).
"""

from __future__ import annotations

import asyncio
import json
import time
import urllib.parse

from ray_tpu.serve.handle import (
    CONTROLLER_NAME,
    DeploymentHandle,
    DeploymentStreamResponse,
)

_ROUTE_TTL_S = 2.0
_REQUEST_TIMEOUT_S = 60.0
_MAX_BODY = 512 * 1024 * 1024

_REASONS = {200: "OK", 404: "Not Found", 408: "Timeout", 500: "Internal"}


def _sse_frame(item) -> bytes:
    """One SSE event per yielded item; multi-line payloads get one
    `data:` line each per the SSE spec."""
    if isinstance(item, bytes):
        payload = item.decode("utf-8", "replace")
    elif isinstance(item, str):
        payload = item
    else:
        payload = json.dumps(item)
    lines = payload.split("\n")
    return ("".join(f"data: {ln}\n" for ln in lines) + "\n").encode()


def _chunk(data: bytes) -> bytes:
    return b"%x\r\n%s\r\n" % (len(data), data)


class ProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._routes: dict[str, tuple] = {}  # prefix → (app, ingress)
        self._handles: dict[str, DeploymentHandle] = {}
        self._routes_ts = 0.0
        self._controller = None
        self._server: asyncio.AbstractServer | None = None
        self._stats = {"requests": 0, "streams": 0, "errors": 0}
        # Actor __init__ runs on the executor thread; the server must
        # live on the runtime loop where handle calls are native.
        from ray_tpu import api as core_api

        asyncio.run_coroutine_threadsafe(
            self._start(host, port), core_api._runtime.loop
        ).result(timeout=30)

    async def _start(self, host: str, port: int):
        self._server = await asyncio.start_server(
            self._handle_conn, host, port
        )

    def get_port(self) -> int:
        return self._server.sockets[0].getsockname()[1]

    def get_stats(self) -> dict:
        return dict(self._stats)

    # ---------------------------------------------------------- routing
    async def _refresh_routes(self, force: bool = False):
        """Poll the controller's route table (loop-native: get_actor /
        handle.result() would deadlock the runtime loop)."""
        now = time.monotonic()
        if not force and now - self._routes_ts < _ROUTE_TTL_S and self._routes:
            return
        from ray_tpu import api as core_api
        from ray_tpu.runtime.core_worker import ActorSubmitTarget

        core = core_api._runtime.core
        if self._controller is None:
            reply = await core.head.call("get_actor", name=CONTROLLER_NAME)
            if not reply["ok"]:
                raise RuntimeError("serve controller is not running")
            self._controller = ActorSubmitTarget(
                reply["actor_id"], reply["addr"]
            )
        try:
            refs = await core.submit_task(
                "get_route_table",
                (),
                {},
                num_returns=1,
                actor=self._controller,
            )
            self._routes = (await core.get(refs))[0]
        except Exception:
            # The controller may have been restarted as a new actor (this
            # proxy is detached and outlives serve.shutdown/serve.run
            # cycles): drop the cached target so the next refresh
            # re-resolves it by name.
            self._controller = None
            raise
        self._routes_ts = time.monotonic()

    def _match_route(self, route: str):
        for prefix in sorted(self._routes, key=len, reverse=True):
            if (
                route == prefix
                or route.startswith(prefix.rstrip("/") + "/")
                or prefix == "/"
            ):
                return prefix
        return None

    def _handle_for(self, match: str) -> DeploymentHandle:
        app_name, ingress = self._routes[match]
        handle = self._handles.get(app_name)
        if handle is None or handle.deployment_name != ingress:
            handle = DeploymentHandle(ingress, app_name)
            self._handles[app_name] = handle
        return handle

    # ------------------------------------------------------- connection
    async def _handle_conn(self, reader, writer):
        try:
            while True:
                keep_alive = await self._handle_one(reader, writer)
                if not keep_alive:
                    break
        except (
            asyncio.IncompleteReadError,
            ConnectionResetError,
            BrokenPipeError,
            TimeoutError,
        ):
            pass
        except Exception:  # noqa: BLE001 - never kill the accept loop
            self._stats["errors"] += 1
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass

    async def _handle_one(self, reader, writer) -> bool:
        request_line = await reader.readline()
        if not request_line:
            return False
        try:
            method, target, version = (
                request_line.decode("latin-1").rstrip("\r\n").split(" ", 2)
            )
        except ValueError:
            await self._respond(writer, 500, b"malformed request line")
            return False
        headers: dict[str, str] = {}
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            if b":" in line:
                k, v = line.decode("latin-1").split(":", 1)
                headers[k.strip().lower()] = v.strip()
        try:
            n = int(headers.get("content-length", 0) or 0)
        except ValueError:
            await self._respond(writer, 500, b"bad content-length")
            return False
        body = b""
        if n:
            if n > _MAX_BODY:
                await self._respond(writer, 500, b"body too large")
                return False
            body = await reader.readexactly(n)
        keep_alive = (
            headers.get("connection", "").lower() != "close"
            and version != "HTTP/1.0"
        )

        self._stats["requests"] += 1
        # Everything below must produce an HTTP response, never a bare
        # connection drop (streaming manages its own error framing).
        try:
            await self._refresh_routes()
            parsed = urllib.parse.urlparse(target)
            match = self._match_route(parsed.path)
            if match is None:
                # A just-deployed app may not be in the cached table yet.
                await self._refresh_routes(force=True)
                match = self._match_route(parsed.path)
            if match is None:
                await self._respond(writer, 404, b"no route", keep_alive)
                return keep_alive

            query = dict(urllib.parse.parse_qsl(parsed.query))
            payload: object = body
            if body:
                try:
                    payload = json.loads(body)
                except ValueError:
                    payload = body
            request = {
                "method": method,
                "path": parsed.path,
                "query": query,
                "headers": headers,
                "body": payload,
            }
            want_stream = (
                "text/event-stream" in headers.get("accept", "")
                or query.get("stream", "").lower() in ("1", "true")
                or (isinstance(payload, dict) and bool(payload.get("stream")))
            )
            handle = self._handle_for(match)
            if want_stream:
                self._stats["streams"] += 1
                return await self._respond_stream(
                    writer, handle, request, keep_alive
                )
            result = await asyncio.wait_for(
                handle.remote(request), _REQUEST_TIMEOUT_S
            )
            if isinstance(result, bytes):
                out = result
            elif isinstance(result, str):
                out = result.encode()
            else:
                out = json.dumps(result).encode()
        except asyncio.TimeoutError:
            self._stats["errors"] += 1
            await self._respond(writer, 408, b"request timed out", keep_alive)
            return keep_alive
        except Exception as e:  # noqa: BLE001 - user/routing error → 500
            self._stats["errors"] += 1
            await self._respond(writer, 500, str(e).encode(), keep_alive)
            return keep_alive
        await self._respond(writer, 200, out, keep_alive)
        return keep_alive

    async def _respond(
        self, writer, status: int, payload: bytes, keep_alive: bool = False
    ):
        reason = _REASONS.get(status, "Unknown")
        conn = "keep-alive" if keep_alive else "close"
        writer.write(
            (
                f"HTTP/1.1 {status} {reason}\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"Connection: {conn}\r\n\r\n"
            ).encode()
            + payload
        )
        await writer.drain()

    async def _respond_stream(
        self, writer, handle: DeploymentHandle, request: dict, keep_alive: bool
    ) -> bool:
        """Stream the handle call as SSE over chunked transfer encoding.
        Headers are written only once the first item (or first error)
        arrives, so pre-stream failures still get a clean HTTP status."""
        stream: DeploymentStreamResponse = handle.options(stream=True).remote(
            request
        )
        agen = stream.__aiter__()
        started = False

        def _sse_headers() -> bytes:
            conn = "keep-alive" if keep_alive else "close"
            return (
                "HTTP/1.1 200 OK\r\n"
                "Content-Type: text/event-stream\r\n"
                "Cache-Control: no-cache\r\n"
                "Transfer-Encoding: chunked\r\n"
                f"Connection: {conn}\r\n\r\n"
            ).encode()

        try:
            while True:
                # Per-item deadline: a replica hung before its next yield
                # must not pin this connection (and its router inflight
                # slot) forever.
                try:
                    item = await asyncio.wait_for(
                        agen.__anext__(), _REQUEST_TIMEOUT_S
                    )
                except StopAsyncIteration:
                    break
                if not started:
                    started = True
                    writer.write(_sse_headers())
                writer.write(_chunk(_sse_frame(item)))
                await writer.drain()
            if not started:
                # Empty stream: still a valid SSE response.
                started = True
                writer.write(_sse_headers())
            writer.write(_chunk(b"data: [DONE]\n\n") + b"0\r\n\r\n")
            await writer.drain()
            return keep_alive
        except (ConnectionResetError, BrokenPipeError):
            # Client went away: stop the replica-side generator.
            await agen.aclose()
            return False
        except Exception as e:  # noqa: BLE001
            self._stats["errors"] += 1
            await agen.aclose()
            if not started:
                await self._respond(writer, 500, str(e).encode(), keep_alive)
                return keep_alive
            # Mid-stream failure: emit an SSE error event, then terminate
            # the chunked body so the client sees a clean end.
            err = json.dumps({"error": str(e)})
            writer.write(
                _chunk(f"event: error\ndata: {err}\n\n".encode())
                + b"0\r\n\r\n"
            )
            await writer.drain()
            return False

    async def shutdown(self):
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        return True
