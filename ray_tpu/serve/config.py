"""Deployment and autoscaling configuration.

(reference: python/ray/serve/config.py AutoscalingConfig /
DeploymentConfig; schema.py)
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class AutoscalingConfig:
    """Scale replicas to hold per-replica ongoing requests near target
    (reference: serve/_private/autoscaling_state.py decision logic)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 2.0


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 5
    # Proxy-enforced deadline for requests routed to this deployment
    # (None → proxy default, 60s). For unary requests this bounds the
    # whole call; for streaming responses it is a per-item idle deadline
    # (the gap between yields), not an end-to-end cap. Reference:
    # Serve's request_timeout_s in HTTPOptions (serve/config.py).
    request_timeout_s: float | None = None
    # Scale-down drain bound for this deployment's replicas: a retiring
    # replica stops accepting, finishes in-flight requests up to this
    # long, then is killed (None → SERVE_DRAIN_TIMEOUT_S). Reference:
    # Serve's graceful_shutdown_timeout_s (serve/config.py).
    drain_timeout_s: float | None = None
    autoscaling_config: AutoscalingConfig | None = None
    ray_actor_options: dict = field(default_factory=dict)
    user_config: dict | None = None

    def to_dict(self) -> dict:
        return {
            "num_replicas": self.num_replicas,
            "max_ongoing_requests": self.max_ongoing_requests,
            "request_timeout_s": self.request_timeout_s,
            "drain_timeout_s": self.drain_timeout_s,
            "autoscaling": None
            if self.autoscaling_config is None
            else vars(self.autoscaling_config),
            "ray_actor_options": dict(self.ray_actor_options),
            "user_config": self.user_config,
        }
