"""Serve request-path telemetry: trace minting, request spans, SLO
histograms, and saturation gauges.

The signal plane for the production-serve arc (ROADMAP): a trace
context is minted at proxy ingress (or adopted from an inbound
``traceparent`` / ``x-request-id`` header) and propagated through
handle dispatch → replica → LLM engine, emitting a connected span tree
per request — ``serve:ingress`` / ``serve:queue`` / ``serve:replica``
/ ``serve:prefill`` / ``serve:decode`` — on the same task-event
pipeline the train spans ride. Rank-0-analogue: the head folds
``serve:ingress`` spans into a per-deployment SLO ledger
(HeadService._serve_request_event) the way it folds ``train:step``
spans into goodput.

Metric labels stay BOUNDED (deployment/app/outcome — never request or
session ids; tpulint TPU403 enforces this); per-request identity rides
on span attributes instead, where cardinality is ring-bounded.

Disable with RAY_TPU_SERVE_TELEMETRY=0: ``begin_request`` then hands
back a shared no-op whose per-request overhead a perf-floor test pins
(tests/test_observability.py), mirroring the train step-telemetry
floor.
"""

from __future__ import annotations

import time
import uuid

from ray_tpu.util import tracing
from ray_tpu.util.metrics import Counter, Gauge, Histogram

_LAT_BOUNDS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 120.0,
)
_TPOT_BOUNDS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0,
)

REQUEST_LATENCY = Histogram(
    "ray_tpu_serve_request_latency_seconds",
    "end-to-end serve request latency at the proxy (ingress to last "
    "byte)",
    boundaries=_LAT_BOUNDS,
    tag_keys=("app", "deployment"),
)
TTFT = Histogram(
    "ray_tpu_serve_ttft_seconds",
    "time to first token/byte at the proxy (for unary requests this "
    "equals the request latency)",
    boundaries=_LAT_BOUNDS,
    tag_keys=("app", "deployment"),
)
TPOT = Histogram(
    "ray_tpu_serve_tpot_seconds",
    "per-output-token time of finished LLM requests (decode seconds / "
    "generated tokens)",
    boundaries=_TPOT_BOUNDS,
    tag_keys=("deployment",),
)
REQUESTS = Counter(
    "ray_tpu_serve_requests_total",
    "serve requests by outcome (ok / error / timeout)",
    tag_keys=("app", "deployment", "outcome"),
)
QUEUE_DEPTH = Gauge(
    "ray_tpu_serve_queue_depth",
    "requests queued or in flight at this handle's router (the "
    "autoscaling demand signal)",
    tag_keys=("app", "deployment"),
)
TARGET_REPLICAS = Gauge(
    "ray_tpu_serve_target_replicas",
    "the controller's current target replica count per deployment (the "
    "autoscaler's output signal)",
    tag_keys=("app", "deployment"),
)
REPLICA_DEATHS = Counter(
    "ray_tpu_serve_replica_deaths_total",
    "typed replica deaths observed by handle routers (the request was "
    "re-dispatched unless retries were exhausted or opted out)",
    tag_keys=("app", "deployment"),
)
RETRIES = Counter(
    "ray_tpu_serve_retries_total",
    "handle-router request re-dispatches after a typed replica "
    "death or draining refusal",
    tag_keys=("app", "deployment", "reason"),
)
BREAKER_OPEN = Gauge(
    "ray_tpu_serve_breaker_open_replicas",
    "replicas this handle router currently holds an OPEN circuit "
    "breaker for (skipped by routing until half-open probes succeed)",
    tag_keys=("app", "deployment"),
)
DRAINED_REPLICAS = Counter(
    "ray_tpu_serve_drained_replicas_total",
    "replicas retired through the scale-down drain protocol, by how "
    "the drain ended (clean = in-flight hit zero, timeout = "
    "SERVE_DRAIN_TIMEOUT_S expired, dead = died mid-drain)",
    tag_keys=("app", "deployment", "outcome"),
)
BATCH_OCCUPANCY = Gauge(
    "ray_tpu_serve_batch_occupancy",
    "occupied fraction of the most recent batch (engine decode slots "
    "or @serve.batch flush)",
    tag_keys=("deployment",),
)
KV_CACHE_UTIL = Gauge(
    "ray_tpu_serve_kv_cache_utilization",
    "occupied fraction of the LLM engine's paged KV pool",
    tag_keys=("deployment",),
)


def enabled() -> bool:
    from ray_tpu._private import config

    return config.get("SERVE_TELEMETRY")


def adopt_or_mint(headers: dict) -> tuple[str, str, str]:
    """(trace_id, ingress_span_id, request_id) for one proxy request.

    An inbound W3C ``traceparent`` (00-<32hex>-<16hex>-..) contributes
    its trace id; else ``x-request-id`` seeds both the request id and a
    derived trace id so retries of the same id land in the same trace;
    else both are minted fresh."""
    trace_id = ""
    request_id = (headers.get("x-request-id") or "").strip()[:128]
    tp = (headers.get("traceparent") or "").strip()
    parts = tp.split("-")
    if len(parts) >= 3 and len(parts[1]) == 32:
        try:
            int(parts[1], 16)
            trace_id = parts[1]
        except ValueError:
            pass
    if not trace_id:
        trace_id = (
            uuid.uuid5(uuid.NAMESPACE_URL, request_id).hex[:16]
            if request_id
            else uuid.uuid4().hex[:16]
        )
    if not request_id:
        request_id = uuid.uuid4().hex[:16]
    return trace_id, uuid.uuid4().hex[:16], request_id


class _NoopRequest:
    """Disabled path: attribute-compatible with RequestTelemetry,
    shared and allocation-free (the perf-floor contract)."""

    __slots__ = ()
    ctx = None
    request_id = ""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def first_byte(self):
        return None

    def finish(self, *a, **kw):
        return None


NOOP_REQUEST = _NoopRequest()


class RequestTelemetry:
    """One proxy request's telemetry: a trace scope for the dispatch
    body plus the ``serve:ingress`` root span + histograms emitted at
    finish(). Used as a context manager around the dispatch so spans
    emitted downstream (queue/replica/engine) parent under the ingress
    span."""

    __slots__ = ("trace_id", "span_id", "request_id", "start", "_ttft",
                 "_token")

    def __init__(self, headers: dict):
        self.trace_id, self.span_id, self.request_id = adopt_or_mint(
            headers
        )
        self.start = time.time()
        self._ttft: float | None = None
        self._token = None

    @property
    def ctx(self) -> tuple[str, str]:
        return (self.trace_id, self.span_id)

    def __enter__(self):
        self._token = tracing._current.set(self.ctx)
        return self

    def __exit__(self, *exc):
        if self._token is not None:
            tracing._current.reset(self._token)
            self._token = None
        return False

    def first_byte(self):
        """Mark time-to-first-token/byte (streams call it on the first
        SSE frame; the unary path lets finish() default it to the full
        latency)."""
        if self._ttft is None:
            self._ttft = time.time() - self.start

    def finish(
        self,
        app: str,
        deployment: str,
        route: str,
        status: int,
        streamed: bool = False,
        items: int = 0,
    ) -> None:
        """Emit the ingress span + per-deployment histograms. Called
        once, after the response (or stream) is fully written."""
        dur = time.time() - self.start
        ttft = self._ttft if self._ttft is not None else dur
        tags = {"app": app, "deployment": deployment}
        REQUEST_LATENCY.observe(dur, tags=tags)
        TTFT.observe(ttft, tags=tags)
        outcome = (
            "ok" if status < 400 else
            "timeout" if status == 408 else "error"
        )
        REQUESTS.inc(tags={**tags, "outcome": outcome})
        tracing.record_span(
            self.trace_id, self.span_id, "", "serve:ingress",
            self.start, dur,
            app=app, deployment=deployment, route=route,
            status=int(status), ttft_s=round(ttft, 6),
            request_id=self.request_id, streamed=bool(streamed),
            items=int(items),
        )


def begin_request(headers: dict):
    """Proxy entry hook: RequestTelemetry when serve telemetry is on,
    the shared no-op otherwise (one config lookup on the disabled
    path)."""
    if not enabled():
        return NOOP_REQUEST
    return RequestTelemetry(headers)


def record_queue_wait(app: str, deployment: str, start: float,
                      dur: float) -> None:
    """Router-side: one replica-slot acquisition, emitted as a
    ``serve:queue`` span under the active (ingress) trace context.
    Rate-limited through the collective flight recorder's high-rate
    sampler so a slot-storm of sub-ms acquisitions cannot evict real
    events from the head's ring buffer."""
    from ray_tpu.collective import flight_recorder

    emit, n = flight_recorder.span_sample(
        f"{app}/{deployment}", "serve:queue", dur
    )
    if not emit:
        return
    attrs = {"app": app, "deployment": deployment}
    if n > 1:
        attrs["sample_rate"] = n
    tracing.emit_span("serve:queue", start, dur, **attrs)


def record_token_span(deployment: str, start: float, dur: float,
                      tokens: int) -> None:
    """Engine-side: one streamed decode delta as a ``serve:token`` span
    under the active trace context, through the same high-rate sampler
    (a 100-token/s stream per request would otherwise be a span storm)."""
    from ray_tpu.collective import flight_recorder

    emit, n = flight_recorder.span_sample(deployment, "serve:token", dur)
    if not emit:
        return
    attrs = {"deployment": deployment, "tokens": int(tokens)}
    if n > 1:
        attrs["sample_rate"] = n
    tracing.emit_span("serve:token", start, dur, **attrs)


def record_engine_phases(deployment: str, timing: dict | None,
                         tokens: int) -> None:
    """Engine-side: emit ``serve:prefill`` and ``serve:decode`` spans
    from the engine's per-request timing (under the active replica span)
    and observe per-output-token time. Safe on partial timing (aborted
    or legacy requests)."""
    if not timing:
        return
    pf_start = timing.get("prefill_start_ts")
    first = timing.get("first_token_ts")
    finish = timing.get("finish_ts")
    if pf_start and first and first >= pf_start:
        tracing.emit_span(
            "serve:prefill", pf_start, first - pf_start,
            deployment=deployment,
            queue_s=round(timing.get("queue_s", 0.0), 6),
        )
    if first and finish and finish >= first:
        decode_s = finish - first
        tracing.emit_span(
            "serve:decode", first, decode_s,
            deployment=deployment, tokens=int(tokens),
        )
        if tokens > 1:
            TPOT.observe(
                decode_s / (tokens - 1), tags={"deployment": deployment}
            )


def set_engine_gauges(deployment: str, active: int, max_batch: int,
                      pages_free: int | None,
                      pages_total: int | None) -> None:
    """Engine pump hook: decode-slot occupancy + paged-KV utilization."""
    if max_batch > 0:
        BATCH_OCCUPANCY.set(
            active / max_batch, tags={"deployment": deployment}
        )
    if pages_total:
        KV_CACHE_UTIL.set(
            (pages_total - (pages_free or 0)) / pages_total,
            tags={"deployment": deployment},
        )
