"""@serve.batch: dynamic request batching inside a replica.

(reference: python/ray/serve/batching.py — single-element calls queue up;
a flusher invokes the wrapped method with a list once max_batch_size is
reached or batch_wait_timeout_s elapses; the wrapped method returns a
list of per-element results.)

On TPU this is the tool that turns concurrent single requests into one
batched forward pass (MXU wants large batches).
"""

from __future__ import annotations

import asyncio
import functools
import inspect


class _BatchQueue:
    def __init__(self, fn, self_arg, max_batch_size: int, timeout_s: float):
        self._fn = fn
        self._self_arg = self_arg
        self._max = max_batch_size
        self._timeout = timeout_s
        self._pending: list[tuple] = []  # (arg, future)
        self._flusher: asyncio.Task | None = None

    async def submit(self, arg):
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._pending.append((arg, fut))
        if len(self._pending) >= self._max:
            self._flush_now()
        elif self._flusher is None or self._flusher.done():
            self._flusher = asyncio.ensure_future(self._delayed_flush())
        return await fut

    async def _delayed_flush(self):
        await asyncio.sleep(self._timeout)
        self._flush_now()

    def _flush_now(self):
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        if self._flusher is not None and not self._flusher.done():
            self._flusher.cancel()
        self._flusher = None
        asyncio.ensure_future(self._run_batch(batch))

    async def _run_batch(self, batch: list[tuple]):
        args = [a for a, _ in batch]
        try:
            if self._self_arg is not None:
                results = self._fn(self._self_arg, args)
            else:
                results = self._fn(args)
            if inspect.isawaitable(results):
                results = await results
            if len(results) != len(args):
                raise ValueError(
                    f"batched function returned {len(results)} results "
                    f"for a batch of {len(args)}"
                )
            for (_, fut), r in zip(batch, results):
                if not fut.done():
                    fut.set_result(r)
        except Exception as e:  # noqa: BLE001 - fan the error out
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)


def batch(_fn=None, *, max_batch_size: int = 10, batch_wait_timeout_s: float = 0.01):
    """Decorator for methods/functions taking a list of items.

    The decorated callable is invoked with single items; the underlying
    implementation receives a list and returns a same-length list.
    """

    def deco(fn):
        attr = f"__serve_batch_queue_{fn.__name__}"

        @functools.wraps(fn)
        async def method_wrapper(self, arg):
            q = getattr(self, attr, None)
            if q is None:
                q = _BatchQueue(fn, self, max_batch_size, batch_wait_timeout_s)
                setattr(self, attr, q)
            return await q.submit(arg)

        @functools.wraps(fn)
        async def func_wrapper(arg):
            q = func_wrapper.__dict__.get("_queue")
            if q is None:
                q = _BatchQueue(fn, None, max_batch_size, batch_wait_timeout_s)
                func_wrapper._queue = q
            return await q.submit(arg)

        params = list(inspect.signature(fn).parameters)
        return method_wrapper if params and params[0] == "self" else func_wrapper

    if _fn is not None:
        return deco(_fn)
    return deco
