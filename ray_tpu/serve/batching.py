"""@serve.batch: dynamic request batching inside a replica.

(reference: python/ray/serve/batching.py — single-element calls queue up;
a flusher invokes the wrapped method with a list once max_batch_size is
reached or batch_wait_timeout_s elapses; the wrapped method returns a
list of per-element results.)

On TPU this is the tool that turns concurrent single requests into one
batched forward pass (MXU wants large batches).
"""

from __future__ import annotations

import asyncio
import functools
import inspect


class _BatchQueue:
    def __init__(self, fn, self_arg, max_batch_size: int, timeout_s: float):
        self._fn = fn
        self._self_arg = self_arg
        self._max = max_batch_size
        self._timeout = timeout_s
        self._pending: list[tuple] = []  # (arg, future)
        self._flusher: asyncio.Task | None = None
        # Telemetry label: the deployment this queue batches for when
        # known (first submit runs under the request context), else the
        # wrapped function's name — bounded either way.
        self._label = getattr(fn, "__qualname__", "batch")

    async def submit(self, arg):
        from ray_tpu.serve import telemetry as stel
        from ray_tpu.serve.context import get_request_context

        dep = get_request_context().deployment
        if dep:
            self._label = dep
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._pending.append((arg, fut))
        if stel.enabled():
            stel.BATCH_OCCUPANCY.set(
                len(self._pending) / max(1, self._max),
                tags={"deployment": self._label},
            )
        if len(self._pending) >= self._max:
            self._flush_now()
        elif self._flusher is None or self._flusher.done():
            self._flusher = asyncio.ensure_future(self._delayed_flush())
        return await fut

    async def _delayed_flush(self):
        await asyncio.sleep(self._timeout)
        self._flush_now()

    def _flush_now(self):
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        if self._flusher is not None and not self._flusher.done():
            self._flusher.cancel()
        self._flusher = None
        asyncio.ensure_future(self._run_batch(batch))

    async def _run_batch(self, batch: list[tuple]):
        import time

        from ray_tpu.serve import telemetry as stel

        args = [a for a, _ in batch]
        start = time.time()
        try:
            if self._self_arg is not None:
                results = self._fn(self._self_arg, args)
            else:
                results = self._fn(args)
            if inspect.isawaitable(results):
                results = await results
            if len(results) != len(args):
                raise ValueError(
                    f"batched function returned {len(results)} results "
                    f"for a batch of {len(args)}"
                )
            for (_, fut), r in zip(batch, results):
                if not fut.done():
                    fut.set_result(r)
            if stel.enabled():
                # One sampled span per flush: occupancy + wait are the
                # signals that tune max_batch_size/batch_wait_timeout_s.
                from ray_tpu.collective import flight_recorder
                from ray_tpu.util import tracing

                dur = time.time() - start
                emit, n = flight_recorder.span_sample(
                    self._label, "serve:batch", dur
                )
                if emit:
                    attrs = {
                        "deployment": self._label,
                        "batch_size": len(batch),
                        "occupancy": round(len(batch) / max(1, self._max), 3),
                    }
                    if n > 1:
                        attrs["sample_rate"] = n
                    tracing.emit_span("serve:batch", start, dur, **attrs)
        # tpulint: allow(broad-except reason=the batch failure is fanned out to every caller's future - nothing is swallowed)
        except Exception as e:  # noqa: BLE001 - fan the error out
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)


def batch(_fn=None, *, max_batch_size: int = 10, batch_wait_timeout_s: float = 0.01):
    """Decorator for methods/functions taking a list of items.

    The decorated callable is invoked with single items; the underlying
    implementation receives a list and returns a same-length list.
    """

    def deco(fn):
        attr = f"__serve_batch_queue_{fn.__name__}"

        @functools.wraps(fn)
        async def method_wrapper(self, arg):
            q = getattr(self, attr, None)
            if q is None:
                q = _BatchQueue(fn, self, max_batch_size, batch_wait_timeout_s)
                setattr(self, attr, q)
            return await q.submit(arg)

        @functools.wraps(fn)
        async def func_wrapper(arg):
            q = func_wrapper.__dict__.get("_queue")
            if q is None:
                q = _BatchQueue(fn, None, max_batch_size, batch_wait_timeout_s)
                func_wrapper._queue = q
            return await q.submit(arg)

        params = list(inspect.signature(fn).parameters)
        return method_wrapper if params and params[0] == "self" else func_wrapper

    if _fn is not None:
        return deco(_fn)
    return deco
