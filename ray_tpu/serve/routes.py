"""Controller route-table polling shared by the data-plane ingresses
(HTTP proxy + gRPC ingress). One implementation so controller-restart
recovery semantics stay in sync (reference: proxy_router.py — the
reference's proxies share one router/route-table updater the same way).
"""

from __future__ import annotations

import time

DEFAULT_TIMEOUT_S = 60.0


class RouteTablePoller:
    """TTL-cached view of the controller's route table: prefix →
    (app, ingress_deployment, request_timeout_s|None).

    Loop-native (runs on the runtime loop — get_actor/handle.result()
    would deadlock it). A failed poll drops the cached controller
    target so the next refresh re-resolves by name: the controller may
    have been restarted as a new actor while this ingress (detached)
    outlived a serve.shutdown/serve.run cycle.
    """

    def __init__(self, ttl_s: float = 2.0):
        self.routes: dict[str, tuple] = {}
        self._ttl_s = ttl_s
        self._ts = 0.0
        self._controller = None

    async def refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._ts < self._ttl_s and self.routes:
            return
        from ray_tpu import api as core_api
        from ray_tpu.runtime.core_worker import ActorSubmitTarget
        from ray_tpu.serve.handle import CONTROLLER_NAME

        core = core_api._runtime.core
        if self._controller is None:
            reply = await core.head.call("get_actor", name=CONTROLLER_NAME)
            if not reply["ok"]:
                raise RuntimeError("serve controller is not running")
            self._controller = ActorSubmitTarget(
                reply["actor_id"], reply["addr"]
            )
        try:
            refs = await core.submit_task(
                "get_route_table",
                (),
                {},
                num_returns=1,
                actor=self._controller,
            )
            self.routes = (await core.get(refs))[0]
        except Exception:
            self._controller = None
            raise
        self._ts = time.monotonic()

    def by_app(self) -> dict[str, tuple]:
        """app → (ingress_deployment, request_timeout_s)."""
        out = {}
        for app_name, ingress, *rest in self.routes.values():
            timeout = (
                rest[0] if rest and rest[0] is not None else DEFAULT_TIMEOUT_S
            )
            out[app_name] = (ingress, timeout)
        return out
