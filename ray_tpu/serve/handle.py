"""DeploymentHandle: the client-side router to a deployment's replicas.

(reference: python/ray/serve/handle.py:757 DeploymentHandle →
_private/router.py AsyncioRouter with power-of-two-choices replica
picking over queue-length caps, request_router/; replica membership is
pushed by long-poll in the reference — here the router polls the
controller's versioned replica list and refreshes on miss/death.)

All routing state lives on the runtime event loop, so in-flight counts
need no locks. ``remote()`` works from sync code (driver threads, the
HTTP proxy) and from async code running on the runtime loop (other
replicas, the controller).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
import uuid
import zlib
from dataclasses import dataclass

from ray_tpu import api as core_api
from ray_tpu.runtime.core_worker import ActorSubmitTarget

logger = logging.getLogger("ray_tpu.serve")

CONTROLLER_NAME = "_SERVE_CONTROLLER"
_REFRESH_S = 2.0


@dataclass
class _ReplicaTarget:
    actor_id: str
    addr: str
    max_ongoing: int


class _Breaker:
    """Per-replica circuit breaker (router-side). CLOSED routes
    normally; SERVE_BREAKER_FAILURES consecutive typed failures OPEN it
    (the replica is skipped by ``_pick``); after SERVE_BREAKER_RESET_S
    it goes HALF-OPEN and admits exactly one probe request — success
    CLOSES it, failure re-OPENS it. All state lives on the runtime
    event loop, like the rest of the router."""

    __slots__ = ("failures", "opened_at", "probing")

    def __init__(self):
        self.failures = 0
        self.opened_at: float | None = None
        self.probing = False

    def state(self, now: float, reset_s: float) -> str:
        if self.opened_at is None:
            return "closed"
        if now - self.opened_at >= reset_s:
            return "half_open"
        return "open"

    def allow(self, now: float, reset_s: float) -> bool:
        """May a request be dispatched to this replica right now?
        Half-open admits a single in-flight probe."""
        st = self.state(now, reset_s)
        if st == "closed":
            return True
        if st == "open":
            return False
        if self.probing:
            return False
        self.probing = True
        return True

    def routable(self, now: float, reset_s: float) -> bool:
        """Pure check (no probe consumed) for the router's
        no-replica-available clock: an open breaker that has not yet
        reached half-open is the only unroutable state."""
        return self.state(now, reset_s) != "open"

    def record_failure(self, now: float, threshold: int) -> None:
        self.failures += 1
        self.probing = False
        if self.opened_at is not None:
            self.opened_at = now  # half-open probe failed: re-open
        elif self.failures >= threshold:
            self.opened_at = now  # closed → open

    def record_success(self) -> None:
        self.failures = 0
        self.opened_at = None
        self.probing = False


class DeploymentResponse:
    """Future-like result of a handle call (reference: handle.py
    DeploymentResponse). ``result()`` from sync code; ``await`` from
    async code on the runtime loop."""

    def __init__(self, inner, sync: bool):
        self._inner = inner  # concurrent.futures.Future | asyncio.Task
        self._sync = sync

    def result(self, timeout: float | None = None):
        if not self._sync:
            raise RuntimeError(
                "result() would deadlock on the runtime loop; use "
                "`await response` in async code"
            )
        return self._inner.result(timeout)

    def __await__(self):
        if self._sync:
            # Bridge a concurrent future into the awaiting loop.
            return asyncio.wrap_future(self._inner).__await__()
        return self._inner.__await__()


class DeploymentStreamResponse:
    """Iterator over a streaming handle call's yields (reference:
    handle.py DeploymentResponseGenerator). Async-iterate on the runtime
    loop (proxy, composed replicas); sync-iterate from driver threads.
    Items arrive incrementally as the replica yields them."""

    def __init__(self, agen, sync: bool):
        self._agen = agen
        self._sync = sync

    def __aiter__(self):
        if not self._sync:
            return self._agen

        # Foreign event loop (sync=True means the caller is NOT on the
        # runtime loop): drive the router generator on the runtime loop
        # and bridge each item — iterating it directly would attach rpc
        # futures to the wrong loop.
        async def bridge():
            while True:
                fut = asyncio.run_coroutine_threadsafe(
                    self._agen.__anext__(), core_api._runtime.loop
                )
                try:
                    item = await asyncio.wrap_future(fut)
                except StopAsyncIteration:
                    return
                yield item

        return bridge()

    def __iter__(self):
        if not self._sync:
            raise RuntimeError(
                "sync iteration would deadlock on the runtime loop; use "
                "`async for` in async code"
            )
        return self

    def __next__(self):
        fut = asyncio.run_coroutine_threadsafe(
            self._agen.__anext__(), core_api._runtime.loop
        )
        try:
            return fut.result()
        except StopAsyncIteration:
            raise StopIteration from None

    def close(self):
        """Stop consuming; the replica-side generator is told to stop."""
        if self._sync:
            asyncio.run_coroutine_threadsafe(
                self._agen.aclose(), core_api._runtime.loop
            ).result(timeout=5)
        else:
            asyncio.ensure_future(self._agen.aclose())


def _is_draining_refusal(e: Exception) -> bool:
    """Did a replica refuse the request because it is draining? The
    typed error arrives either directly or wrapped in RayTaskError
    (with the original in .cause, or stringified when the cause could
    not travel)."""
    from ray_tpu.exceptions import RayTaskError, ReplicaDrainingError

    if isinstance(e, ReplicaDrainingError):
        return True
    if isinstance(e, RayTaskError):
        return isinstance(
            getattr(e, "cause", None), ReplicaDrainingError
        ) or "ReplicaDrainingError" in str(e)
    return False


class _Router:
    def __init__(self, deployment_name: str, app_name: str):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._controller: ActorSubmitTarget | None = None
        self._replicas: list[_ReplicaTarget] = []
        self._version = -1
        self._last_refresh = 0.0
        self._inflight: dict[str, int] = {}
        # Requests waiting for a replica slot; reported to the controller
        # as autoscaling demand (reference: handles push queued-request
        # metrics to the controller, serve/_private/router.py).
        self._queued = 0
        self._reporter: asyncio.Task | None = None
        # Stable across the router's life, unique across processes (id()
        # values repeat across address spaces and would alias demand
        # reports at the controller).
        self._router_id = uuid.uuid4().hex
        # model_id → (version, replicas ordered by affinity hash); the
        # order only changes when the replica set does.
        self._affinity: dict[str, tuple[int, list[_ReplicaTarget]]] = {}
        # actor_id → circuit breaker. Keyed by actor id (not list
        # position) so a dead replica the controller still lists for a
        # few missed polls stays skipped across refreshes.
        self._breakers: dict[str, _Breaker] = {}
        # Serializes the controller get_replicas RPC: N queued requests
        # forcing refreshes at once must produce ONE poll, not N
        # (instrumented under RAY_TPU_SANITIZE=1).
        from ray_tpu._private import sanitize

        self._refresh_lock = sanitize.maybe_async_lock(
            "serve.handle.refresh"
        )

    def _demand(self) -> int:
        return self._queued + sum(self._inflight.values())

    def _ensure_reporter(self):
        if self._reporter is None or self._reporter.done():
            self._reporter = asyncio.ensure_future(self._report_loop())

    async def _report_loop(self):
        """Report demand while there is any; exit after a short idle
        period (a final 0 report) so dropped handles don't leak an
        eternal task + RPC stream."""
        from ray_tpu.serve import telemetry as stel

        router_id = self._router_id
        idle_since = None
        tel_on = stel.enabled()
        try:
            while True:
                demand = self._demand()
                if tel_on:
                    # Same cadence as the autoscaling demand report: the
                    # queue-depth gauge IS that signal, scrapeable.
                    stel.QUEUE_DEPTH.set(
                        demand,
                        tags={"app": self.app_name,
                              "deployment": self.deployment_name},
                    )
                controller = await self._resolve_controller()
                await self._call_actor(
                    controller,
                    "record_handle_demand",
                    self.deployment_name,
                    self.app_name,
                    router_id,
                    demand,
                )
                if demand == 0:
                    if idle_since is None:
                        idle_since = time.monotonic()
                    elif time.monotonic() - idle_since > 3.0:
                        return
                else:
                    idle_since = None
                await asyncio.sleep(0.3)
        except Exception:  # noqa: BLE001 - controller gone; stop quietly
            logger.debug(
                "handle demand reporter for %s/%s stopped "
                "(controller unreachable)",
                self.app_name, self.deployment_name, exc_info=True,
            )

    async def _core(self):
        core = core_api._runtime.core
        if core is None:
            raise RuntimeError("ray_tpu.init() has not been called")
        return core

    async def _resolve_controller(self):
        if self._controller is None:
            core = await self._core()
            reply = await core.head.call("get_actor", name=CONTROLLER_NAME)
            if not reply["ok"]:
                raise RuntimeError(
                    "serve controller is not running (serve.run first)"
                )
            self._controller = ActorSubmitTarget(
                reply["actor_id"], reply["addr"]
            )
        return self._controller

    async def _call_actor(self, target: ActorSubmitTarget, method, *args):
        core = await self._core()
        refs = await core.submit_task(
            method, args, {}, num_returns=1, actor=target
        )
        values = await core.get(refs)
        return values[0]

    async def _refresh(self, force: bool = False):
        # Forced refreshes (saturation, replica death) are still rate
        # limited so N queued requests don't hammer the controller with
        # N/20ms get_replicas calls exactly when the system is loaded.
        min_interval = 0.1 if force else _REFRESH_S
        if time.monotonic() - self._last_refresh < min_interval:
            return
        async with self._refresh_lock:
            # Re-check under the lock: the poll a concurrent waiter just
            # finished IS this waiter's refresh.
            if time.monotonic() - self._last_refresh < min_interval:
                return
            controller = await self._resolve_controller()
            version, replicas = await self._call_actor(
                controller, "get_replicas", self.deployment_name,
                self.app_name,
            )
            self._last_refresh = time.monotonic()
            if version != self._version:
                self._version = version
                self._replicas = [_ReplicaTarget(*r) for r in replicas]
                self._inflight = {
                    r.actor_id: self._inflight.get(r.actor_id, 0)
                    for r in self._replicas
                }
                # Orderings cached against the old replica set are dead
                # weight now; dropping the whole map also bounds its
                # growth across high-cardinality model ids.
                self._affinity.clear()
                # Breakers for replicas the controller no longer lists
                # are dead weight too — but entries for still-listed
                # replicas survive (a dead replica stays listed for a
                # few missed polls; its open breaker is what keeps it
                # skipped meanwhile).
                listed = {r.actor_id for r in self._replicas}
                for aid in list(self._breakers):
                    if aid not in listed:
                        del self._breakers[aid]

    def _breaker_allows(self, actor_id: str, now: float,
                        reset_s: float) -> bool:
        """Pure pick-eligibility: open → no; half-open with a probe
        already in flight → no. The probe itself is consumed only for
        the replica ``_pick`` actually returns (``_consume_probe``)."""
        br = self._breakers.get(actor_id)
        if br is None:
            return True
        st = br.state(now, reset_s)
        return st == "closed" or (st == "half_open" and not br.probing)

    def _consume_probe(self, replica: _ReplicaTarget, now: float,
                       reset_s: float) -> _ReplicaTarget:
        br = self._breakers.get(replica.actor_id)
        if br is not None:
            br.allow(now, reset_s)  # half-open: claims the single probe
        return replica

    def _has_routable(self) -> bool:
        """Any replica a request could EVER land on right now —
        saturation (in-flight at cap) still counts as routable (the
        request queues), only dead/open-breaker replicas don't. The
        guard deciding whether a slot wait is queueing or an outage."""
        if not self._replicas:
            return False
        from ray_tpu._private import config

        now = time.monotonic()
        reset_s = config.get("SERVE_BREAKER_RESET_S")
        return any(
            br is None or br.routable(now, reset_s)
            for br in (
                self._breakers.get(r.actor_id) for r in self._replicas
            )
        )

    def _record_replica_failure(self, actor_id: str):
        from ray_tpu._private import config

        br = self._breakers.setdefault(actor_id, _Breaker())
        br.record_failure(
            time.monotonic(), config.get("SERVE_BREAKER_FAILURES")
        )
        self._update_breaker_gauge()

    def _record_replica_success(self, actor_id: str):
        br = self._breakers.get(actor_id)
        if br is not None and (br.opened_at is not None or br.failures):
            br.record_success()
            self._update_breaker_gauge()

    def _update_breaker_gauge(self):
        from ray_tpu.serve import telemetry as stel

        if not stel.enabled():
            return
        stel.BREAKER_OPEN.set(
            sum(
                1 for br in self._breakers.values()
                if br.opened_at is not None
            ),
            tags={"app": self.app_name,
                  "deployment": self.deployment_name},
        )

    @staticmethod
    def _is_replica_death(e: Exception) -> bool:
        """Typed replica-death detection: the actor's worker is gone or
        its connection dropped mid-call. User exceptions (RayTaskError)
        are NOT deaths — they propagate to the caller untouched."""
        from ray_tpu.exceptions import ActorDiedError
        from ray_tpu._private import rpc

        return isinstance(
            e, (ActorDiedError, rpc.ConnectionLost, rpc.RpcError)
        )

    @staticmethod
    def _retry_max() -> int:
        from ray_tpu._private import config

        return config.get("SERVE_RETRY_MAX")

    @staticmethod
    def _retry_backoff(attempt: int) -> float:
        """Exponential per-retry backoff, capped at 1s: the surviving
        replicas are absorbing the dead one's load exactly now — a
        stampede of instant retries is the last thing they need."""
        from ray_tpu._private import config

        base = config.get("SERVE_RETRY_BACKOFF_S")
        return min(1.0, base * (2 ** max(0, attempt - 1)))

    def _count_retry(self, reason: str):
        from ray_tpu.serve import telemetry as stel

        if stel.enabled():
            stel.RETRIES.inc(
                tags={"app": self.app_name,
                      "deployment": self.deployment_name,
                      "reason": reason},
            )

    def _count_death(self):
        from ray_tpu.serve import telemetry as stel

        if stel.enabled():
            stel.REPLICA_DEATHS.inc(
                tags={"app": self.app_name,
                      "deployment": self.deployment_name},
            )

    def _drop_replica(self, actor_id: str):
        """Forget a replica ahead of the controller (typed death or
        draining refusal observed first-hand): stop picking it NOW; the
        next version bump reconciles the authoritative list."""
        self._replicas = [
            r for r in self._replicas if r.actor_id != actor_id
        ]
        # The controller may not bump the version for several missed
        # polls; cached affinity orderings still point at the dead
        # replica until then.
        self._affinity.clear()

    def _pick(self, model_id: str) -> _ReplicaTarget | None:
        from ray_tpu._private import config

        now = time.monotonic()
        reset_s = config.get("SERVE_BREAKER_RESET_S")
        avail = [
            r
            for r in self._replicas
            if self._inflight.get(r.actor_id, 0) < r.max_ongoing
            and self._breaker_allows(r.actor_id, now, reset_s)
        ]
        if not avail:
            return None
        if model_id:
            # Hash-affinity for multiplexed models: keep a model's
            # requests on a stable replica so its LRU cache stays warm
            # (reference approximates this with cache-locality routing,
            # multiplex.py); spill down the ordering when saturated.
            # crc32, not hash(): PYTHONHASHSEED randomization would send
            # the same model to different replicas from different
            # processes, thrashing every replica's model LRU.
            if len(self._affinity) > 4096:  # hard cap per router
                self._affinity.clear()
            cached = self._affinity.get(model_id)
            if cached is None or cached[0] != self._version:
                ordered = sorted(
                    self._replicas,
                    key=lambda r: zlib.crc32(
                        f"{model_id}:{r.actor_id}".encode()
                    ),
                )
                self._affinity[model_id] = (self._version, ordered)
            else:
                ordered = cached[1]
            for r in ordered:
                if self._inflight.get(r.actor_id, 0) < r.max_ongoing \
                        and self._breaker_allows(r.actor_id, now, reset_s):
                    return self._consume_probe(r, now, reset_s)
            return None
        if len(avail) == 1:
            return self._consume_probe(avail[0], now, reset_s)
        a, b = random.sample(avail, 2)
        return self._consume_probe(
            a
            if self._inflight.get(a.actor_id, 0)
            <= self._inflight.get(b.actor_id, 0)
            else b,
            now, reset_s,
        )

    def _request_ctx(self, model_id: str) -> dict:
        """Per-call request context shipped to the replica. When serve
        telemetry is on and a trace context is active (a proxy ingress
        span, or any caller running under a span), it rides along so
        the replica's spans join the same tree."""
        ctx = {
            "request_id": uuid.uuid4().hex[:16],
            "multiplexed_model_id": model_id,
            "app_name": self.app_name,
            "deployment": self.deployment_name,
        }
        from ray_tpu.serve import telemetry as stel

        if stel.enabled():
            from ray_tpu.util import tracing

            active = tracing.active_context()
            if active is not None:
                ctx["trace"] = list(active)
        return ctx

    async def _acquire_replica_traced(self, model_id: str) -> _ReplicaTarget:
        """_acquire_replica plus a ``serve:queue`` span covering the
        wait for a replica slot — the queueing phase of the request
        span tree (sampled under storm, see telemetry.record_queue_wait)."""
        from ray_tpu.serve import telemetry as stel

        if not stel.enabled():
            return await self._acquire_replica(model_id)
        q_start = time.time()
        replica = await self._acquire_replica(model_id)
        stel.record_queue_wait(
            self.app_name, self.deployment_name, q_start,
            time.time() - q_start,
        )
        return replica

    async def _acquire_replica(self, model_id: str) -> _ReplicaTarget:
        """Wait for a replica slot. Saturated-but-alive replicas queue
        indefinitely (backpressure, reported as autoscaling demand);
        NO routable replica at all — none known, or every one dead,
        draining, or breaker-open — for SERVE_UNAVAILABLE_TIMEOUT_S
        raises the typed NoReplicaAvailableError instead of hanging
        (the proxy's 503 + Retry-After)."""
        from ray_tpu._private import config

        waiting = False
        unroutable_since: float | None = None
        try:
            while True:
                await self._refresh()
                replica = self._pick(model_id)
                if replica is not None:
                    return replica
                if self._has_routable():
                    unroutable_since = None
                else:
                    now = time.monotonic()
                    if unroutable_since is None:
                        unroutable_since = now
                    bound = config.get("SERVE_UNAVAILABLE_TIMEOUT_S")
                    if now - unroutable_since >= bound:
                        from ray_tpu.exceptions import (
                            NoReplicaAvailableError,
                        )

                        raise NoReplicaAvailableError(
                            self.deployment_name,
                            self.app_name,
                            retry_after_s=max(
                                1.0,
                                config.get("SERVE_BREAKER_RESET_S"),
                            ),
                        )
                if not waiting:
                    waiting = True
                    self._queued += 1
                await self._refresh(force=True)
                await asyncio.sleep(0.02)
        finally:
            if waiting:
                self._queued -= 1

    async def route_and_call(
        self,
        method_name: str,
        args: tuple,
        kwargs: dict,
        model_id: str = "",
        retry_on_failure: bool = True,
    ):
        # Resolve composed-handle responses passed as arguments.
        args = tuple(
            [await a if isinstance(a, DeploymentResponse) else a for a in args]
        )
        kwargs = {
            k: (await v if isinstance(v, DeploymentResponse) else v)
            for k, v in kwargs.items()
        }
        ctx = self._request_ctx(model_id)
        self._ensure_reporter()
        deaths = 0
        drain_hops = 0
        while True:
            replica = await self._acquire_replica_traced(model_id)
            self._inflight[replica.actor_id] = (
                self._inflight.get(replica.actor_id, 0) + 1
            )
            try:
                result = await self._call_actor(
                    ActorSubmitTarget(replica.actor_id, replica.addr),
                    "handle_request",
                    method_name,
                    args,
                    kwargs,
                    ctx,
                )
                self._record_replica_success(replica.actor_id)
                return result
            except Exception as e:  # noqa: BLE001
                if _is_draining_refusal(e):
                    # The replica is retiring (scale-down drain) and
                    # REFUSED the request before starting it — always
                    # safe to re-dispatch, even for non-idempotent
                    # calls, and it never burns a death retry. Bounded
                    # anyway: an entire replica set draining at once
                    # must end in NoReplicaAvailableError, not a spin.
                    drain_hops += 1
                    if drain_hops <= 10:
                        self._drop_replica(replica.actor_id)
                        self._count_retry("draining")
                        await self._refresh(force=True)
                        continue
                    raise
                if self._is_replica_death(e):
                    # Replica died mid-request: open/advance its
                    # breaker, drop it, and re-route with backoff.
                    # NOTE: at-least-once — the dead replica may
                    # already have executed the request. Non-idempotent
                    # callers opt out via
                    # .options(retry_on_failure=False).
                    self._record_replica_failure(replica.actor_id)
                    self._count_death()
                    if retry_on_failure and deaths < self._retry_max():
                        deaths += 1
                        self._drop_replica(replica.actor_id)
                        self._count_retry("death")
                        await asyncio.sleep(self._retry_backoff(deaths))
                        await self._refresh(force=True)
                        continue
                raise
            finally:
                if replica.actor_id in self._inflight:
                    self._inflight[replica.actor_id] -= 1


    async def stream_call(
        self,
        method_name: str,
        args: tuple,
        kwargs: dict,
        model_id: str = "",
        retry_on_failure: bool = True,
    ):
        """Async generator: route to a replica and yield the streaming
        actor call's items as they arrive (reference: streaming handle
        calls, serve/handle.py `handle.options(stream=True)`). Re-routes
        on replica death only before the first item has been yielded."""
        args = tuple(
            [await a if isinstance(a, DeploymentResponse) else a for a in args]
        )
        kwargs = {
            k: (await v if isinstance(v, DeploymentResponse) else v)
            for k, v in kwargs.items()
        }
        ctx = self._request_ctx(model_id)
        self._ensure_reporter()
        core = await self._core()
        deaths = 0
        drain_hops = 0
        while True:
            replica = await self._acquire_replica_traced(model_id)
            self._inflight[replica.actor_id] = (
                self._inflight.get(replica.actor_id, 0) + 1
            )
            yielded = False
            try:
                task_id = await core.submit_task(
                    "handle_request_streaming",
                    (method_name, args, kwargs, ctx),
                    {},
                    num_returns="streaming",
                    actor=ActorSubmitTarget(replica.actor_id, replica.addr),
                )
                try:
                    while True:
                        entry = await core.next_generator_item(task_id)
                        if entry[0] == "done":
                            self._record_replica_success(replica.actor_id)
                            return
                        if entry[0] == "error":
                            raise entry[1]
                        value = (
                            await core.get(
                                [core_api.ObjectRef(entry[1], core.addr)]
                            )
                        )[0]
                        yielded = True
                        yield value
                finally:
                    # Consumer broke out early (or terminal entry already
                    # cleaned up — then this is a no-op): abandon the
                    # stream so the replica stops producing.
                    await core.close_generator(task_id)
            except GeneratorExit:
                raise
            except Exception as e:  # noqa: BLE001
                if not yielded and _is_draining_refusal(e):
                    # Retiring replica refused before starting the
                    # stream: always re-routable (see route_and_call).
                    drain_hops += 1
                    if drain_hops <= 10:
                        self._drop_replica(replica.actor_id)
                        self._count_retry("draining")
                        await self._refresh(force=True)
                        continue
                    raise
                if self._is_replica_death(e):
                    self._record_replica_failure(replica.actor_id)
                    self._count_death()
                    # Re-route only before the first yield: a consumer
                    # that already saw items cannot be transparently
                    # replayed — it gets the TYPED death (fail fast,
                    # never a hang) and decides about a fresh request.
                    if (
                        retry_on_failure
                        and not yielded
                        and deaths < self._retry_max()
                    ):
                        deaths += 1
                        self._drop_replica(replica.actor_id)
                        self._count_retry("death")
                        await asyncio.sleep(self._retry_backoff(deaths))
                        await self._refresh(force=True)
                        continue
                raise
            finally:
                if replica.actor_id in self._inflight:
                    self._inflight[replica.actor_id] -= 1


class DeploymentHandle:
    """Serializable, lazy handle: resolves the controller and replica
    set on first call, so it can be shipped into replicas for model
    composition (reference: handles injected for `.bind()` children)."""

    def __init__(
        self,
        deployment_name: str,
        app_name: str = "default",
        method_name: str = "__call__",
        multiplexed_model_id: str = "",
        retry_on_failure: bool = True,
        stream: bool = False,
    ):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._method_name = method_name
        self._model_id = multiplexed_model_id
        self._retry = retry_on_failure
        self._stream = stream
        self._router: _Router | None = None

    def __reduce__(self):
        return (
            DeploymentHandle,
            (
                self.deployment_name,
                self.app_name,
                self._method_name,
                self._model_id,
                self._retry,
                self._stream,
            ),
        )

    def options(
        self,
        *,
        method_name: str | None = None,
        multiplexed_model_id: str | None = None,
        retry_on_failure: bool | None = None,
        stream: bool | None = None,
    ) -> "DeploymentHandle":
        h = DeploymentHandle(
            self.deployment_name,
            self.app_name,
            method_name or self._method_name,
            self._model_id
            if multiplexed_model_id is None
            else multiplexed_model_id,
            self._retry if retry_on_failure is None else retry_on_failure,
            self._stream if stream is None else stream,
        )
        h._router = self._router  # share routing state across options()
        return h

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def _get_router(self) -> _Router:
        if self._router is None:
            self._router = _Router(self.deployment_name, self.app_name)
        return self._router

    def remote(self, *args, **kwargs):
        router = self._get_router()
        loop = core_api._runtime.loop
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if self._stream:
            agen = router.stream_call(
                self._method_name, args, kwargs, self._model_id, self._retry
            )
            return DeploymentStreamResponse(agen, sync=running is not loop)
        coro = router.route_and_call(
            self._method_name, args, kwargs, self._model_id, self._retry
        )
        if running is loop:
            return DeploymentResponse(asyncio.ensure_future(coro), sync=False)
        fut = asyncio.run_coroutine_threadsafe(coro, loop)
        return DeploymentResponse(fut, sync=True)

    def __repr__(self):
        return (
            f"DeploymentHandle({self.app_name}/{self.deployment_name}"
            f".{self._method_name})"
        )
