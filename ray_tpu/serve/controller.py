"""ServeController: the reconciliation brain of serve.

(reference: python/ray/serve/_private/controller.py:106 ServeController —
owns application/deployment target state, reconciles replica actors to
target counts (deployment_state.py), restarts dead replicas, and applies
autoscaling decisions from replica-reported queue lengths
(autoscaling_state.py).)

Runs as a detached named actor. Mutating RPCs are sync methods (the core
worker executes them in arrival order, serializing state changes); the
control loop is a long-lived async method running concurrently, which
talks to replicas through the core worker's coroutine API directly (it
cannot block the loop thread).

The control loop closes the serve signal plane (PR 9) into actions:

- **SLO-driven autoscaling** — demand (replica ongoing + handle-router
  queued) sets the desired replica count; the head serve ledger's SLO
  alert boosts it; hysteresis + cooldown knobs (``SERVE_AUTOSCALE_*``)
  keep an oscillating load from flapping the target. Decisions are
  reported to the head (``serve_autoscale_report``) and exported as the
  ``ray_tpu_serve_target_replicas`` gauge.
- **Zero-drop scale-down** — victims retire through a drain protocol:
  removed from the routed replica list (version bump), told to refuse
  new requests (typed ``ReplicaDrainingError`` the router re-routes
  on), killed only once in-flight work hits zero or
  ``SERVE_DRAIN_TIMEOUT_S`` expires.
- **Replica-kill survival** — dead replicas (3 missed polls, or a
  router's typed death observation) are dropped and replacements start
  on healthy, non-draining nodes; when slices are labeled, replicas
  spread across slice fault domains so one slice preemption cannot take
  out every replica.
"""

from __future__ import annotations

import asyncio
import logging
import time

from ray_tpu import api as core_api
from ray_tpu.runtime.core_worker import ActorSubmitTarget
from ray_tpu.serve.replica import ReplicaActor

_CONTROL_PERIOD_S = 0.25

logger = logging.getLogger(__name__)


def desired_replicas(
    ongoing: float,
    target_ongoing: float,
    min_replicas: int,
    max_replicas: int,
    slo_alert: bool = False,
    slo_boost: bool = True,
) -> int:
    """Demand-derived replica count: enough replicas to hold per-replica
    ongoing requests near target, plus one while the head reports the
    deployment's SLO alert ON (the ledger saw attainment below target —
    demand alone is lagging, so lean in)."""
    if ongoing > 0:
        want = int(-(-ongoing // max(target_ongoing, 1e-9)))
    else:
        want = min_replicas
    if slo_alert and slo_boost:
        want += 1
    return max(min_replicas, min(max_replicas, want))


def autoscale_decision(
    state: dict,
    desired: int,
    now: float,
    *,
    min_replicas: int,
    max_replicas: int,
    up_cooldown_s: float,
    down_cooldown_s: float,
    hysteresis: float,
) -> "str | None":
    """One autoscale step: move ``state['target']`` toward ``desired``
    with hysteresis and cooldowns. Pure against ``state`` + ``now`` so
    the no-flapping property is unit-testable without a cluster.

    - A desired within ``hysteresis * target`` of the current target is
      treated as equal (dead-band against demand noise).
    - Scale-UP applies after ``up_cooldown_s`` since the last up move.
    - Scale-DOWN requires desired to stay below target CONTINUOUSLY for
      ``down_cooldown_s``, and then drops only to the MAXIMUM desired
      seen during that window — an oscillating load keeps the window
      max high, so the target never chases the troughs (no flapping).

    Returns the decision reason ("up"/"down") when the target moved,
    else None. ``state`` keys used: target, last_scale_up,
    low_since, desired_window (list of (ts, desired))."""
    desired = max(min_replicas, min(max_replicas, int(desired)))
    target = state["target"]
    if abs(desired - target) <= hysteresis * target:
        desired = target
    window = state.setdefault("desired_window", [])
    window.append((now, desired))
    cutoff = now - max(down_cooldown_s, 1e-9)
    while window and window[0][0] < cutoff:
        window.pop(0)
    if desired > target:
        state["low_since"] = None
        if now - state.get("last_scale_up", -1e9) >= up_cooldown_s:
            state["target"] = desired
            state["last_scale_up"] = now
            return "up"
        return None
    if desired < target:
        if state.get("low_since") is None:
            state["low_since"] = now
            return None
        if now - state["low_since"] < down_cooldown_s:
            return None
        new_target = max(
            min_replicas,
            max((d for _ts, d in window), default=desired),
        )
        state["low_since"] = None
        if new_target < target:
            state["target"] = new_target
            return "down"
        return None
    state["low_since"] = None
    return None


def pick_spread_slice(
    replicas: list, healthy_slices: "set[str]"
) -> "str | None":
    """Least-populated healthy slice for the next replica (cross-slice
    spread, the serve twin of STRICT_SPREAD_SLICES): one slice
    preemption then takes out at most ceil(n/len(slices)) replicas.
    None when the cluster has no labeled slices."""
    if not healthy_slices:
        return None
    counts = {sid: 0 for sid in healthy_slices}
    for r in replicas:
        sid = r.get("slice")
        if sid in counts:
            counts[sid] += 1
    return min(sorted(counts), key=lambda sid: counts[sid])


class ServeController:
    def __init__(self):
        # (app_name, deployment_name) → deployment record
        self._deployments: dict[tuple, dict] = {}
        # app_name → {"ingress": str, "route_prefix": str, "deployments": [str]}
        self._apps: dict[str, dict] = {}
        # (app, dep) → {router_id: (demand, t)} — handle-reported queued +
        # in-flight requests (reference: handles push queue metrics used
        # by autoscaling_state.py; replica-side ongoing alone misses
        # client-side queuing).
        self._handle_demand: dict[tuple, dict] = {}
        self._shutdown = False
        # Strong refs to fire-and-forget tasks (kills, background replica
        # starts): the loop only weak-refs tasks, so an untracked one can
        # be GC'd before it runs.
        self._bg_tasks: set = set()
        # Head serve-SLO ledger cache ("app/deployment" → public row),
        # refreshed at SERVE_AUTOSCALE_INTERVAL_S inside the control
        # loop — the signal-plane read feeding scale decisions.
        self._slo_cache: dict[str, dict] = {}
        self._slo_last_poll = 0.0
        # (healthy slice ids, node_id → slice_id) from the last
        # cluster_status poll — replica cross-slice spread input.
        self._slices: tuple[set, dict] = (set(), {})
        # Serializes replica-set surgery between the reconcile pass and
        # teardown drains scheduled from the sync RPC thread (both run
        # on the runtime loop, but interleave across awaits).
        from ray_tpu._private import sanitize

        self._drain_lock = sanitize.maybe_async_lock(
            "serve.controller.drain"
        )

    def _spawn_bg(self, coro) -> None:
        task = asyncio.ensure_future(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    # ------------------------------------------------------ deploy API
    def deploy_application(self, app_name: str, spec: dict):
        """spec: {"route_prefix", "ingress", "deployments": [
        {"name", "callable", "init_args", "init_kwargs", "config"}]}"""
        self._apps[app_name] = {
            "ingress": spec["ingress"],
            "route_prefix": spec.get("route_prefix", f"/{app_name}"),
            "deployments": [d["name"] for d in spec["deployments"]],
        }
        for d in spec["deployments"]:
            key = (app_name, d["name"])
            cfg = d["config"]
            auto = cfg.get("autoscaling")
            target = (
                auto["min_replicas"] if auto else cfg.get("num_replicas", 1)
            )
            old = self._deployments.get(key)
            if old is not None and old["replicas"]:
                # Redeploy replaces replicas all-at-once so new code /
                # config actually takes effect (reference: deployment
                # version change triggers replica restart,
                # deployment_state.py).
                asyncio.run_coroutine_threadsafe(
                    self._drain_replicas(dict(old)), core_api._runtime.loop
                )
            now = time.monotonic()
            self._deployments[key] = {
                "name": d["name"],
                "app": app_name,
                "callable": d["callable"],
                "init_args": d["init_args"],
                "init_kwargs": d["init_kwargs"],
                "config": cfg,
                "target": target,
                # replicas: list of dicts {actor_id, addr, node_id,
                # slice, started_at, misses}
                "replicas": [],
                # Scale-down victims mid-drain: {**replica,
                # "drain_deadline": monotonic}. Not routed (absent from
                # get_replicas), killed once idle or past deadline.
                "draining_replicas": [],
                "version": (old["version"] + 1) if old else 0,
                "last_scale_up": now,
                "low_since": None,
                "desired_window": [],
                "status": "UPDATING",
                # Last autoscale decision (surfaced via serve_stats):
                # {"desired", "reason", "ts"}.
                "autoscale": None,
                "reported_target": None,
            }
        return True

    def update_target(
        self, app_name: str, deployment_name: str, target: int
    ) -> int:
        """Operator/bench scaling entry point: set a deployment's
        target replica count directly. Clamped to the autoscaling
        bounds when an autoscaling_config exists (the policy loop keeps
        adjusting from the new value). Scale-down still goes through
        the drain protocol — this is the same target the reconcile
        loop converges on, not a kill."""
        dep = self._deployments.get((app_name, deployment_name))
        if dep is None:
            raise ValueError(
                f"no deployment {deployment_name!r} in app {app_name!r}"
            )
        target = int(target)
        auto = dep["config"].get("autoscaling")
        if auto is not None:
            target = max(
                auto["min_replicas"], min(auto["max_replicas"], target)
            )
        else:
            target = max(0, target)
        dep["target"] = target
        return target

    def delete_application(self, app_name: str):
        """Blocks until replicas are torn down (sync actor methods run on
        the executor thread, so waiting on the loop-side drain is safe)."""
        app = self._apps.pop(app_name, None)
        if app is None:
            return False
        drains = []
        loop = core_api._runtime.loop
        for name in app["deployments"]:
            dep = self._deployments.pop((app_name, name), None)
            self._handle_demand.pop((app_name, name), None)
            if dep:
                dep["target"] = 0
                drains.append(
                    asyncio.run_coroutine_threadsafe(
                        self._drain_replicas(dep), loop
                    )
                )
        for d in drains:
            try:
                d.result(timeout=10)
            except Exception:
                logger.debug(
                    "replica drain failed during app teardown",
                    exc_info=True,
                )
        return True

    async def _drain_replicas(self, dep: dict):
        """App-teardown kill of every replica (deploy replacement or
        delete): unlike scale-down there is nothing to hand traffic to,
        so this is immediate, not the graceful drain protocol."""
        core = core_api._runtime.core
        async with self._drain_lock:
            victims = list(dep["replicas"]) + list(
                dep.get("draining_replicas") or []
            )
            dep["replicas"] = []
            dep["draining_replicas"] = []
        for r in victims:
            try:
                await core.kill_actor(r["actor_id"], r["addr"])
            # tpulint: allow(broad-except reason=drain kill of a replica that already died is the expected race, nothing to handle)
            except Exception:
                pass

    # ------------------------------------------------------- query API
    def get_replicas(self, deployment_name: str, app_name: str):
        dep = self._deployments.get((app_name, deployment_name))
        if dep is None:
            raise ValueError(
                f"no deployment {deployment_name!r} in app {app_name!r}"
            )
        max_ongoing = dep["config"].get("max_ongoing_requests", 5)
        return (
            dep["version"],
            [(r["actor_id"], r["addr"], max_ongoing) for r in dep["replicas"]],
        )

    def record_handle_demand(
        self, deployment_name: str, app_name: str, router_id: str, demand: int
    ):
        self._handle_demand.setdefault((app_name, deployment_name), {})[
            router_id
        ] = (int(demand), time.monotonic())
        return True

    def get_route_table(self):
        """prefix → (app, ingress, request_timeout_s|None). The timeout
        is the ingress deployment's request_timeout_s so the proxy can
        enforce a per-deployment deadline without extra RPCs."""
        table = {}
        for name, app in self._apps.items():
            dep = self._deployments.get((name, app["ingress"]))
            timeout = (
                dep["config"].get("request_timeout_s") if dep else None
            )
            table[app["route_prefix"]] = (name, app["ingress"], timeout)
        return table

    def get_status(self):
        out = {}
        for (app, name), dep in self._deployments.items():
            out.setdefault(app, {})[name] = {
                "status": dep["status"],
                "target": dep["target"],
                "replicas": len(dep["replicas"]),
                "draining": len(dep.get("draining_replicas") or []),
                "autoscale": dep.get("autoscale"),
            }
        return out

    def graceful_shutdown(self):
        self._shutdown = True
        for app in list(self._apps):
            self.delete_application(app)
        return True

    # ---------------------------------------------------- control loop
    async def run_control_loop(self):
        """Reconcile forever (reference: ServeController.run_control_loop).
        Runs as a concurrent async actor task; returns on shutdown."""
        while not self._shutdown:
            try:
                await self._reconcile_once()
            except Exception:
                # Keep the loop alive, but never silently: a reconcile
                # pass that throws every period is an outage in the
                # making (stuck migrations, zombie replicas).
                logger.warning(
                    "serve reconcile pass failed; retrying next period",
                    exc_info=True,
                )
            await asyncio.sleep(_CONTROL_PERIOD_S)
        return True

    async def _cluster_view(self, core) -> tuple[set, set, dict]:
        """(draining node ids, healthy slice ids, node_id→slice_id) —
        one cluster_status poll per reconcile pass, so drain migration
        starts within a control period of the notice and replica
        placement sees the live slice fault domains."""
        try:
            reply = await core.head.call("cluster_status")
        except Exception:
            # Head busy or too old: skip migration/spread this period
            # rather than stall the reconcile.
            logger.debug("cluster_status poll failed", exc_info=True)
            return set(), set(), {}
        draining = set(reply.get("draining") or {})
        node_slice: dict = {}
        healthy: set = set()
        for sid, rec in (reply.get("slices") or {}).items():
            for nid in rec.get("nodes") or []:
                node_slice[nid] = sid
            if rec.get("state") == "healthy":
                healthy.add(sid)
        return draining, healthy, node_slice

    async def _poll_slo(self, core) -> None:
        """Refresh the head serve-SLO ledger cache (attainment, alert,
        request rate per deployment) at SERVE_AUTOSCALE_INTERVAL_S —
        the ledger-read half of the autoscaling loop."""
        from ray_tpu._private import config

        now = time.monotonic()
        if now - self._slo_last_poll < config.get(
            "SERVE_AUTOSCALE_INTERVAL_S"
        ):
            return
        self._slo_last_poll = now
        try:
            reply = await core.head.call("serve_stats")
            self._slo_cache = reply.get("deployments") or {}
        except Exception:
            # A missing ledger only withholds the SLO boost; the demand
            # signal still drives scaling.
            logger.debug("serve_stats poll failed", exc_info=True)

    async def _reconcile_once(self):
        core = core_api._runtime.core
        draining, healthy_slices, node_slice = await self._cluster_view(
            core
        )
        self._slices = (healthy_slices, node_slice)
        await self._poll_slo(core)
        # Evict handle-demand entries from routers that stopped reporting.
        now = time.monotonic()
        for key, routers in list(self._handle_demand.items()):
            for rid, (_d, t) in list(routers.items()):
                if now - t > 10.0:
                    del routers[rid]
            if not routers:
                del self._handle_demand[key]
        for dep in list(self._deployments.values()):
            # 1. Health-check replicas: poll stats, drop the dead.
            stats = await self._poll_stats(core, dep)
            # 2. Autoscale: demand + head SLO ledger → target, through
            # the hysteresis/cooldown policy.
            auto = dep["config"].get("autoscaling")
            if auto is not None and stats is not None:
                self._autoscale(dep, auto, stats)
            # 3. Reconcile count toward target. Starts are background
            # tasks: a deployment whose __init__ jits a model for tens of
            # seconds must not freeze health checks and autoscaling for
            # every other deployment (the stale-record guard in
            # _start_replica makes late completions safe).
            #
            # Drain migration is start-replacement-FIRST: replicas on
            # draining nodes keep serving (they don't count as healthy,
            # so `need` starts their replacements off-node — the head
            # and the draining node itself refuse new placements there)
            # and are retired only once the healthy count reaches
            # target, via the victim ordering below. Requests never see
            # a window with fewer than `target` live replicas.
            n_draining = sum(
                1
                for r in dep["replicas"]
                if r.get("node_id") in draining
            )
            healthy = len(dep["replicas"]) - n_draining
            need = dep["target"] - healthy - dep.get("starting", 0)
            for _ in range(max(0, need)):
                dep["starting"] = dep.get("starting", 0) + 1
                self._spawn_bg(self._start_replica_tracked(core, dep))
            async with self._drain_lock:
                excess = len(dep["replicas"]) - dep["target"]
                if excess > 0:
                    victims = self._scale_down_victims(
                        dep["replicas"], draining, excess
                    )
                    self._begin_drain(dep, victims)
                await self._advance_drains(core, dep)
            dep["status"] = (
                "HEALTHY"
                if len(dep["replicas"]) == dep["target"] and not n_draining
                else "UPDATING"
            )
            self._report_autoscale(core, dep)

    @staticmethod
    def _scale_down_victims(
        replicas: list, draining: set, excess: int
    ) -> list:
        """Scale-down victim order: draining-node replicas first (they
        are already condemned), then the flakiest (highest health-poll
        miss count), then the OLDEST — never the newest/warmest, which
        the previous `replicas[-excess:]` slice used to kill right after
        paying their cold start."""
        ranked = sorted(
            replicas,
            key=lambda r: (
                0 if r.get("node_id") in draining else 1,
                -r.get("misses", 0),
                r.get("started_at", 0.0),
            ),
        )
        return ranked[:excess]

    async def _poll_stats(self, core, dep: dict):
        if not dep["replicas"]:
            return {"num_ongoing_requests": 0}

        async def poll_one(r):
            refs = await core.submit_task(
                "get_stats",
                (),
                {},
                num_returns=1,
                actor=ActorSubmitTarget(r["actor_id"], r["addr"]),
            )
            return (await core.get(refs, timeout=2))[0]

        # Concurrent polls: one hung replica must not stall the control
        # loop for every other deployment.
        results = await asyncio.gather(
            *(poll_one(r) for r in dep["replicas"]), return_exceptions=True
        )
        total_ongoing = 0
        dead = []
        for r, s in zip(list(dep["replicas"]), results):
            if isinstance(s, BaseException):
                # A single missed poll is not death: a replica blocked in
                # a long jit compile (first LLM request) must not be
                # killed mid-request. Three consecutive misses ≈ 3 control
                # periods + timeouts before we declare it gone.
                r["misses"] = r.get("misses", 0) + 1
                if r["misses"] >= 3:
                    dead.append(r)
            else:
                r["misses"] = 0
                total_ongoing += s["num_ongoing_requests"]
        if dead:
            dep["replicas"] = [r for r in dep["replicas"] if r not in dead]
            dep["version"] += 1
            # Kill what we dropped: a replica that stopped answering polls
            # would otherwise keep running (and keep its chips) forever
            # while a replacement starts beside it.
            for r in dead:
                self._spawn_bg(self._kill_quietly(core, r))
        return {"num_ongoing_requests": total_ongoing}

    @staticmethod
    async def _kill_quietly(core, r: dict):
        try:
            await core.kill_actor(r["actor_id"], r["addr"])
        # tpulint: allow(broad-except reason=quiet kill by contract - replica already dead is the common case)
        except Exception:
            pass

    def _autoscale(self, dep: dict, auto: dict, stats: dict):
        """One policy step: demand signal (replica ongoing ∨ handle-
        router queued+in-flight) plus the head ledger's SLO alert →
        desired count → hysteresis/cooldown decision
        (autoscale_decision). The decision and its inputs land in
        dep["autoscale"] for serve_stats/status surfacing."""
        from ray_tpu._private import config

        if not config.get("SERVE_AUTOSCALE"):
            return
        now = time.monotonic()
        reported = self._handle_demand.get((dep["app"], dep["name"]), {})
        handle_demand = sum(
            d for d, t in reported.values() if now - t < 2.0
        )
        ongoing = max(stats["num_ongoing_requests"], handle_demand)
        slo = self._slo_cache.get(f'{dep["app"]}/{dep["name"]}') or {}
        desired = desired_replicas(
            ongoing,
            auto["target_ongoing_requests"],
            auto["min_replicas"],
            auto["max_replicas"],
            slo_alert=bool(slo.get("alert")),
            slo_boost=config.get("SERVE_AUTOSCALE_SLO_BOOST"),
        )
        reason = autoscale_decision(
            dep,
            desired,
            now,
            min_replicas=auto["min_replicas"],
            max_replicas=auto["max_replicas"],
            up_cooldown_s=max(
                auto.get("upscale_delay_s", 0.0) or 0.0,
                config.get("SERVE_AUTOSCALE_UP_COOLDOWN_S"),
            ),
            down_cooldown_s=max(
                auto.get("downscale_delay_s", 0.0) or 0.0,
                config.get("SERVE_AUTOSCALE_DOWN_COOLDOWN_S"),
            ),
            hysteresis=config.get("SERVE_AUTOSCALE_HYSTERESIS"),
        )
        dep["autoscale"] = {
            "desired": desired,
            "ongoing": ongoing,
            "slo_alert": bool(slo.get("alert")),
            "reason": reason or (dep.get("autoscale") or {}).get("reason"),
            "ts": time.time(),
        }

    # ------------------------------------------------ scale-down drain
    def _begin_drain(self, dep: dict, victims: list):
        """Scale-down, step 1 (zero-drop contract): victims leave the
        routed replica list NOW (version bump → routers refresh away),
        are told to refuse new requests (typed refusal covers routers
        holding the stale list), and keep serving their in-flight
        requests until _advance_drains retires them. Caller holds
        _drain_lock."""
        from ray_tpu._private import config

        if not victims:
            return
        timeout = dep["config"].get("drain_timeout_s")
        if timeout is None:
            timeout = config.get("SERVE_DRAIN_TIMEOUT_S")
        now = time.monotonic()
        dep["replicas"] = [
            r for r in dep["replicas"] if r not in victims
        ]
        dep["version"] += 1
        for r in victims:
            r["drain_deadline"] = now + timeout
            dep["draining_replicas"].append(r)
            self._spawn_bg(self._prepare_drain(r))

    async def _prepare_drain(self, r: dict):
        core = core_api._runtime.core
        try:
            refs = await core.submit_task(
                "prepare_drain", (), {}, num_returns=1,
                actor=ActorSubmitTarget(r["actor_id"], r["addr"]),
            )
            await core.get(refs, timeout=5)
        except Exception:
            # Unreachable victim: _advance_drains sees the failed stats
            # poll and retires it as "dead" — the drain still converges.
            logger.debug(
                "prepare_drain failed; replica will be reaped",
                exc_info=True,
            )

    async def _advance_drains(self, core, dep: dict):
        """Scale-down, step 2: retire each draining replica once its
        in-flight count hits zero (clean), its drain deadline passes
        (timeout), or it stops answering (dead). Caller holds
        _drain_lock."""
        pending = dep.get("draining_replicas") or []
        if not pending:
            return
        now = time.monotonic()
        done: list = []
        for r in pending:
            outcome = None
            try:
                refs = await core.submit_task(
                    "get_stats", (), {}, num_returns=1,
                    actor=ActorSubmitTarget(r["actor_id"], r["addr"]),
                )
                stats = (await core.get(refs, timeout=2))[0]
                if stats["num_ongoing_requests"] <= 0:
                    outcome = "clean"
                elif now >= r["drain_deadline"]:
                    outcome = "timeout"
            # tpulint: allow(broad-except reason=a draining replica that stopped answering is retired as dead; the drain must converge, not diagnose)
            except Exception:
                outcome = "dead"
            if outcome is not None:
                done.append((r, outcome))
        for r, outcome in done:
            dep["draining_replicas"].remove(r)
            self._spawn_bg(self._kill_quietly(core, r))
            if outcome == "timeout":
                logger.warning(
                    "serve %s/%s: draining replica exceeded its "
                    "drain timeout with requests still in flight; "
                    "killing it",
                    dep["app"], dep["name"],
                )
            from ray_tpu.serve import telemetry as stel

            if stel.enabled():
                stel.DRAINED_REPLICAS.inc(
                    tags={"app": dep["app"], "deployment": dep["name"],
                          "outcome": outcome},
                )

    def _report_autoscale(self, core, dep: dict):
        """Push this deployment's target (and last decision) to the
        head — serve_stats' "autoscale" block and the head-owned
        ray_tpu_serve_target_replicas gauge — and mirror it on the
        controller-local gauge. Sent on change only; the head keeps the
        last word."""
        if dep.get("reported_target") == (
            dep["target"], len(dep["replicas"]),
        ):
            return
        dep["reported_target"] = (dep["target"], len(dep["replicas"]))
        from ray_tpu.serve import telemetry as stel

        if stel.enabled():
            stel.TARGET_REPLICAS.set(
                dep["target"],
                tags={"app": dep["app"], "deployment": dep["name"]},
            )
        auto = dep.get("autoscale") or {}
        self._spawn_bg(
            self._send_autoscale_report(
                core,
                app=dep["app"],
                deployment=dep["name"],
                target=dep["target"],
                replicas=len(dep["replicas"]),
                draining=len(dep.get("draining_replicas") or []),
                desired=auto.get("desired"),
                reason=auto.get("reason"),
            )
        )

    @staticmethod
    async def _send_autoscale_report(core, **kw):
        try:
            await core.head.call("serve_autoscale_report", **kw)
        except Exception:
            # Old head / head mid-restart: the gauge still updated
            # locally; the next change retries.
            logger.debug("serve_autoscale_report failed", exc_info=True)

    async def _start_replica_tracked(self, core, dep: dict):
        try:
            await self._start_replica(core, dep)
        except Exception:
            # e.g. no feasible node; the reconcile loop will retry next
            # period, so log rather than let asyncio print "Task
            # exception was never retrieved".
            logger.debug("replica start failed; will retry",
                         exc_info=True)
        finally:
            dep["starting"] = max(0, dep.get("starting", 0) - 1)

    async def _start_replica(self, core, dep: dict):
        cfg = dep["config"]
        actor_opts = cfg.get("ray_actor_options", {})
        resources = dict(actor_opts.get("resources", {}))
        if "num_cpus" in actor_opts:
            resources["CPU"] = float(actor_opts["num_cpus"])
        if "num_tpus" in actor_opts:
            resources["TPU"] = float(actor_opts["num_tpus"])
        create_kwargs = dict(
            resources=resources or {"CPU": 0.1},
            max_concurrency=max(
                2 * cfg.get("max_ongoing_requests", 5), 16
            ),
        )
        # Cross-slice spread: when the cluster labels slices, pin the
        # new replica to the healthy slice currently holding the fewest
        # of this deployment's replicas (the serve twin of
        # STRICT_SPREAD_SLICES — one slice preemption cannot take out
        # every replica). Falls back to unconstrained placement when
        # the chosen slice cannot take the lease: availability beats
        # spread.
        healthy_slices, _node_slice = self._slices
        spread = pick_spread_slice(
            dep["replicas"] + (dep.get("draining_replicas") or []),
            healthy_slices,
        )
        args = (
            dep["name"],
            dep["callable"],
            dep["init_args"],
            dep["init_kwargs"],
            cfg.get("user_config"),
        )
        if spread is not None:
            try:
                actor_id, addr = await core.create_actor(
                    ReplicaActor, args, {},
                    scheduling={"labels_hard": {"slice": spread}},
                    **create_kwargs,
                )
            # tpulint: allow(broad-except reason=spread placement is best-effort; the unconstrained fallback below keeps the deployment available)
            except Exception:
                logger.debug(
                    "cross-slice replica placement on slice %r failed; "
                    "falling back to unconstrained placement",
                    spread, exc_info=True,
                )
                spread = None
        if spread is None:
            actor_id, addr = await core.create_actor(
                ReplicaActor, args, {}, **create_kwargs,
            )
        # Which node hosts this replica? The head's actor registry knows
        # — needed so drain migration and victim selection can reason
        # per-node.
        node_id = None
        try:
            info = await core.head.call("get_actor", actor_id=actor_id)
            if info.get("ok"):
                node_id = info.get("node_id")
        except Exception:
            logger.debug("actor node lookup failed; node_id unknown",
                         exc_info=True)
        key = (dep["app"], dep["name"])
        if self._deployments.get(key) is not dep:
            # The deployment was redeployed or deleted while this replica
            # was starting; appending to the stale record would orphan it.
            await self._kill_quietly(core, {"actor_id": actor_id, "addr": addr})
            return
        dep["replicas"].append(
            {
                "actor_id": actor_id,
                "addr": addr,
                "node_id": node_id,
                "slice": self._slices[1].get(node_id),
                "started_at": time.monotonic(),
            }
        )
        dep["version"] += 1
