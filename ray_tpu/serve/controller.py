"""ServeController: the reconciliation brain of serve.

(reference: python/ray/serve/_private/controller.py:106 ServeController —
owns application/deployment target state, reconciles replica actors to
target counts (deployment_state.py), restarts dead replicas, and applies
autoscaling decisions from replica-reported queue lengths
(autoscaling_state.py).)

Runs as a detached named actor. Mutating RPCs are sync methods (the core
worker executes them in arrival order, serializing state changes); the
control loop is a long-lived async method running concurrently, which
talks to replicas through the core worker's coroutine API directly (it
cannot block the loop thread).
"""

from __future__ import annotations

import asyncio
import logging
import time

from ray_tpu import api as core_api
from ray_tpu.runtime.core_worker import ActorSubmitTarget
from ray_tpu.serve.replica import ReplicaActor

_CONTROL_PERIOD_S = 0.25

logger = logging.getLogger(__name__)


class ServeController:
    def __init__(self):
        # (app_name, deployment_name) → deployment record
        self._deployments: dict[tuple, dict] = {}
        # app_name → {"ingress": str, "route_prefix": str, "deployments": [str]}
        self._apps: dict[str, dict] = {}
        # (app, dep) → {router_id: (demand, t)} — handle-reported queued +
        # in-flight requests (reference: handles push queue metrics used
        # by autoscaling_state.py; replica-side ongoing alone misses
        # client-side queuing).
        self._handle_demand: dict[tuple, dict] = {}
        self._shutdown = False
        # Strong refs to fire-and-forget tasks (kills, background replica
        # starts): the loop only weak-refs tasks, so an untracked one can
        # be GC'd before it runs.
        self._bg_tasks: set = set()

    def _spawn_bg(self, coro) -> None:
        task = asyncio.ensure_future(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    # ------------------------------------------------------ deploy API
    def deploy_application(self, app_name: str, spec: dict):
        """spec: {"route_prefix", "ingress", "deployments": [
        {"name", "callable", "init_args", "init_kwargs", "config"}]}"""
        self._apps[app_name] = {
            "ingress": spec["ingress"],
            "route_prefix": spec.get("route_prefix", f"/{app_name}"),
            "deployments": [d["name"] for d in spec["deployments"]],
        }
        for d in spec["deployments"]:
            key = (app_name, d["name"])
            cfg = d["config"]
            auto = cfg.get("autoscaling")
            target = (
                auto["min_replicas"] if auto else cfg.get("num_replicas", 1)
            )
            old = self._deployments.get(key)
            if old is not None and old["replicas"]:
                # Redeploy replaces replicas all-at-once so new code /
                # config actually takes effect (reference: deployment
                # version change triggers replica restart,
                # deployment_state.py).
                asyncio.run_coroutine_threadsafe(
                    self._drain_replicas(dict(old)), core_api._runtime.loop
                )
            now = time.monotonic()
            self._deployments[key] = {
                "name": d["name"],
                "app": app_name,
                "callable": d["callable"],
                "init_args": d["init_args"],
                "init_kwargs": d["init_kwargs"],
                "config": cfg,
                "target": target,
                # replicas: list of dicts {actor_id, addr}
                "replicas": [],
                "version": (old["version"] + 1) if old else 0,
                "last_scale_up": now,
                "last_scale_down": now,
                "status": "UPDATING",
            }
        return True

    def delete_application(self, app_name: str):
        """Blocks until replicas are torn down (sync actor methods run on
        the executor thread, so waiting on the loop-side drain is safe)."""
        app = self._apps.pop(app_name, None)
        if app is None:
            return False
        drains = []
        loop = core_api._runtime.loop
        for name in app["deployments"]:
            dep = self._deployments.pop((app_name, name), None)
            self._handle_demand.pop((app_name, name), None)
            if dep:
                dep["target"] = 0
                drains.append(
                    asyncio.run_coroutine_threadsafe(
                        self._drain_replicas(dep), loop
                    )
                )
        for d in drains:
            try:
                d.result(timeout=10)
            except Exception:
                logger.debug(
                    "replica drain failed during app teardown",
                    exc_info=True,
                )
        return True

    async def _drain_replicas(self, dep: dict):
        core = core_api._runtime.core
        for r in list(dep["replicas"]):
            try:
                await core.kill_actor(r["actor_id"], r["addr"])
            # tpulint: allow(broad-except reason=drain kill of a replica that already died is the expected race, nothing to handle)
            except Exception:
                pass
        dep["replicas"] = []

    # ------------------------------------------------------- query API
    def get_replicas(self, deployment_name: str, app_name: str):
        dep = self._deployments.get((app_name, deployment_name))
        if dep is None:
            raise ValueError(
                f"no deployment {deployment_name!r} in app {app_name!r}"
            )
        max_ongoing = dep["config"].get("max_ongoing_requests", 5)
        return (
            dep["version"],
            [(r["actor_id"], r["addr"], max_ongoing) for r in dep["replicas"]],
        )

    def record_handle_demand(
        self, deployment_name: str, app_name: str, router_id: str, demand: int
    ):
        self._handle_demand.setdefault((app_name, deployment_name), {})[
            router_id
        ] = (int(demand), time.monotonic())
        return True

    def get_route_table(self):
        """prefix → (app, ingress, request_timeout_s|None). The timeout
        is the ingress deployment's request_timeout_s so the proxy can
        enforce a per-deployment deadline without extra RPCs."""
        table = {}
        for name, app in self._apps.items():
            dep = self._deployments.get((name, app["ingress"]))
            timeout = (
                dep["config"].get("request_timeout_s") if dep else None
            )
            table[app["route_prefix"]] = (name, app["ingress"], timeout)
        return table

    def get_status(self):
        out = {}
        for (app, name), dep in self._deployments.items():
            out.setdefault(app, {})[name] = {
                "status": dep["status"],
                "target": dep["target"],
                "replicas": len(dep["replicas"]),
            }
        return out

    def graceful_shutdown(self):
        self._shutdown = True
        for app in list(self._apps):
            self.delete_application(app)
        return True

    # ---------------------------------------------------- control loop
    async def run_control_loop(self):
        """Reconcile forever (reference: ServeController.run_control_loop).
        Runs as a concurrent async actor task; returns on shutdown."""
        while not self._shutdown:
            try:
                await self._reconcile_once()
            except Exception:
                # Keep the loop alive, but never silently: a reconcile
                # pass that throws every period is an outage in the
                # making (stuck migrations, zombie replicas).
                logger.warning(
                    "serve reconcile pass failed; retrying next period",
                    exc_info=True,
                )
            await asyncio.sleep(_CONTROL_PERIOD_S)
        return True

    async def _draining_nodes(self, core) -> set:
        """Node ids the head reports as DRAINING — refreshed every
        reconcile pass so migration starts within one control period of
        the drain notice."""
        try:
            reply = await core.head.call("drain_table")
            return set(reply.get("draining") or {})
        except Exception:
            # Head busy or too old to know drain_table: skip migration
            # this period rather than stall the reconcile.
            logger.debug("drain_table poll failed", exc_info=True)
            return set()

    async def _reconcile_once(self):
        core = core_api._runtime.core
        draining = await self._draining_nodes(core)
        # Evict handle-demand entries from routers that stopped reporting.
        now = time.monotonic()
        for key, routers in list(self._handle_demand.items()):
            for rid, (_d, t) in list(routers.items()):
                if now - t > 10.0:
                    del routers[rid]
            if not routers:
                del self._handle_demand[key]
        for dep in list(self._deployments.values()):
            # 1. Health-check replicas: poll stats, drop the dead.
            stats = await self._poll_stats(core, dep)
            # 2. Autoscale: move target toward ongoing/target ratio.
            auto = dep["config"].get("autoscaling")
            if auto is not None and stats is not None:
                self._autoscale(dep, auto, stats)
            # 3. Reconcile count toward target. Starts are background
            # tasks: a deployment whose __init__ jits a model for tens of
            # seconds must not freeze health checks and autoscaling for
            # every other deployment (the stale-record guard in
            # _start_replica makes late completions safe).
            #
            # Drain migration is start-replacement-FIRST: replicas on
            # draining nodes keep serving (they don't count as healthy,
            # so `need` starts their replacements off-node — the head
            # and the draining node itself refuse new placements there)
            # and are retired only once the healthy count reaches
            # target, via the victim ordering below. Requests never see
            # a window with fewer than `target` live replicas.
            n_draining = sum(
                1
                for r in dep["replicas"]
                if r.get("node_id") in draining
            )
            healthy = len(dep["replicas"]) - n_draining
            need = dep["target"] - healthy - dep.get("starting", 0)
            for _ in range(max(0, need)):
                dep["starting"] = dep.get("starting", 0) + 1
                self._spawn_bg(self._start_replica_tracked(core, dep))
            excess = len(dep["replicas"]) - dep["target"]
            if excess > 0:
                victims = self._scale_down_victims(
                    dep["replicas"], draining, excess
                )
                dep["replicas"] = [
                    r for r in dep["replicas"] if r not in victims
                ]
                dep["version"] += 1
                for r in victims:
                    try:
                        await core.kill_actor(r["actor_id"], r["addr"])
                    # tpulint: allow(broad-except reason=scale-down victim may already be dead; reconcile re-counts next period)
                    except Exception:
                        pass
            dep["status"] = (
                "HEALTHY"
                if len(dep["replicas"]) == dep["target"] and not n_draining
                else "UPDATING"
            )

    @staticmethod
    def _scale_down_victims(
        replicas: list, draining: set, excess: int
    ) -> list:
        """Scale-down victim order: draining-node replicas first (they
        are already condemned), then the flakiest (highest health-poll
        miss count), then the OLDEST — never the newest/warmest, which
        the previous `replicas[-excess:]` slice used to kill right after
        paying their cold start."""
        ranked = sorted(
            replicas,
            key=lambda r: (
                0 if r.get("node_id") in draining else 1,
                -r.get("misses", 0),
                r.get("started_at", 0.0),
            ),
        )
        return ranked[:excess]

    async def _poll_stats(self, core, dep: dict):
        if not dep["replicas"]:
            return {"num_ongoing_requests": 0}

        async def poll_one(r):
            refs = await core.submit_task(
                "get_stats",
                (),
                {},
                num_returns=1,
                actor=ActorSubmitTarget(r["actor_id"], r["addr"]),
            )
            return (await core.get(refs, timeout=2))[0]

        # Concurrent polls: one hung replica must not stall the control
        # loop for every other deployment.
        results = await asyncio.gather(
            *(poll_one(r) for r in dep["replicas"]), return_exceptions=True
        )
        total_ongoing = 0
        dead = []
        for r, s in zip(list(dep["replicas"]), results):
            if isinstance(s, BaseException):
                # A single missed poll is not death: a replica blocked in
                # a long jit compile (first LLM request) must not be
                # killed mid-request. Three consecutive misses ≈ 3 control
                # periods + timeouts before we declare it gone.
                r["misses"] = r.get("misses", 0) + 1
                if r["misses"] >= 3:
                    dead.append(r)
            else:
                r["misses"] = 0
                total_ongoing += s["num_ongoing_requests"]
        if dead:
            dep["replicas"] = [r for r in dep["replicas"] if r not in dead]
            dep["version"] += 1
            # Kill what we dropped: a replica that stopped answering polls
            # would otherwise keep running (and keep its chips) forever
            # while a replacement starts beside it.
            for r in dead:
                self._spawn_bg(self._kill_quietly(core, r))
        return {"num_ongoing_requests": total_ongoing}

    @staticmethod
    async def _kill_quietly(core, r: dict):
        try:
            await core.kill_actor(r["actor_id"], r["addr"])
        # tpulint: allow(broad-except reason=quiet kill by contract - replica already dead is the common case)
        except Exception:
            pass

    def _autoscale(self, dep: dict, auto: dict, stats: dict):
        now = time.monotonic()
        reported = self._handle_demand.get((dep["app"], dep["name"]), {})
        handle_demand = sum(
            d for d, t in reported.values() if now - t < 2.0
        )
        ongoing = max(stats["num_ongoing_requests"], handle_demand)
        desired = max(
            auto["min_replicas"],
            min(
                auto["max_replicas"],
                -(-ongoing // max(auto["target_ongoing_requests"], 1e-9))
                if ongoing
                else auto["min_replicas"],
            ),
        )
        desired = int(desired)
        if desired > dep["target"]:
            if now - dep["last_scale_up"] >= auto.get("upscale_delay_s", 0):
                dep["target"] = desired
                dep["last_scale_up"] = now
        elif desired < dep["target"]:
            if now - dep["last_scale_down"] >= auto.get(
                "downscale_delay_s", 2.0
            ):
                dep["target"] = desired
                dep["last_scale_down"] = now
        else:
            dep["last_scale_down"] = now

    async def _start_replica_tracked(self, core, dep: dict):
        try:
            await self._start_replica(core, dep)
        except Exception:
            # e.g. no feasible node; the reconcile loop will retry next
            # period, so log rather than let asyncio print "Task
            # exception was never retrieved".
            logger.debug("replica start failed; will retry",
                         exc_info=True)
        finally:
            dep["starting"] = max(0, dep.get("starting", 0) - 1)

    async def _start_replica(self, core, dep: dict):
        cfg = dep["config"]
        actor_opts = cfg.get("ray_actor_options", {})
        resources = dict(actor_opts.get("resources", {}))
        if "num_cpus" in actor_opts:
            resources["CPU"] = float(actor_opts["num_cpus"])
        if "num_tpus" in actor_opts:
            resources["TPU"] = float(actor_opts["num_tpus"])
        actor_id, addr = await core.create_actor(
            ReplicaActor,
            (
                dep["name"],
                dep["callable"],
                dep["init_args"],
                dep["init_kwargs"],
                cfg.get("user_config"),
            ),
            {},
            resources=resources or {"CPU": 0.1},
            max_concurrency=max(
                2 * cfg.get("max_ongoing_requests", 5), 16
            ),
        )
        # Which node hosts this replica? The head's actor registry knows
        # — needed so drain migration and victim selection can reason
        # per-node.
        node_id = None
        try:
            info = await core.head.call("get_actor", actor_id=actor_id)
            if info.get("ok"):
                node_id = info.get("node_id")
        except Exception:
            logger.debug("actor node lookup failed; node_id unknown",
                         exc_info=True)
        key = (dep["app"], dep["name"])
        if self._deployments.get(key) is not dep:
            # The deployment was redeployed or deleted while this replica
            # was starting; appending to the stale record would orphan it.
            await self._kill_quietly(core, {"actor_id": actor_id, "addr": addr})
            return
        dep["replicas"].append(
            {
                "actor_id": actor_id,
                "addr": addr,
                "node_id": node_id,
                "started_at": time.monotonic(),
            }
        )
        dep["version"] += 1
