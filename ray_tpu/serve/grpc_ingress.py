"""gRPC Serve ingress: a standard-protocol data plane for non-Python
clients (reference: python/ray/serve/_private/proxy.py:534 ``gRPCProxy``
— the reference runs a gRPC servicer next to the HTTP proxy whose
``unary_unary``/``unary_stream`` handlers bridge into DeploymentHandles;
same shape here over ``raytpu.serve.ServeIngress`` from
``protos/serve.proto``).

Server: ``serve.start_grpc()`` deploys :class:`GrpcIngressActor` as a
detached actor running a ``grpc.aio`` server; any gRPC client in any
language can then call ``raytpu.serve.ServeIngress/Call`` (unary) or
``/Stream`` (server-streaming) using the committed ``.proto``.

The servicer is registered through ``grpc.method_handlers_generic_handler``
with protoc-generated message classes — no grpc_tools codegen needed on
the server, and the wire format is plain protobuf-over-HTTP/2.
"""

from __future__ import annotations

import asyncio
import json

from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.routes import RouteTablePoller

SERVICE_NAME = "raytpu.serve.ServeIngress"
GRPC_INGRESS_NAME = "_serve_grpc_ingress"


def _encode_reply(value, serve_pb2):
    """Pick the reply content_type from the Python value's type
    (mirrors the HTTP proxy's bytes/str/JSON response negotiation)."""
    if isinstance(value, bytes):
        return serve_pb2.ServeReply(payload=value, content_type="bytes")
    if isinstance(value, str):
        return serve_pb2.ServeReply(
            payload=value.encode(), content_type="text"
        )
    return serve_pb2.ServeReply(
        payload=json.dumps(value).encode(), content_type="json"
    )


def _decode_payload(request):
    ctype = request.content_type or "json"
    if ctype == "bytes":
        return request.payload
    if ctype == "text":
        return request.payload.decode()
    if ctype == "json":
        if not request.payload:
            return None
        return json.loads(request.payload.decode())
    raise ValueError(f"unknown content_type {ctype!r}")


def _make_auth_interceptor():
    """grpc.aio server interceptor enforcing the cluster token
    (``authorization: Bearer <AUTH_TOKEN>``). Healthz stays open —
    load balancers probe it without credentials. Built lazily: the
    class must subclass grpc.aio.ServerInterceptor and grpc imports
    stay deferred in this module."""
    import grpc

    class _AuthInterceptor(grpc.aio.ServerInterceptor):
        async def intercept_service(
            self, continuation, handler_call_details
        ):
            if handler_call_details.method.endswith("/Healthz"):
                return await continuation(handler_call_details)
            from ray_tpu._private import config

            token = config.get("AUTH_TOKEN")
            meta = dict(handler_call_details.invocation_metadata or ())
            got = meta.get("authorization", "")
            if token and got == f"Bearer {token}":
                return await continuation(handler_call_details)

            def deny(request_or_iter, context):
                context.abort(
                    grpc.StatusCode.UNAUTHENTICATED,
                    "missing or invalid authorization metadata "
                    "(expected: Bearer <cluster token>)",
                )
                yield  # pragma: no cover - abort raises first

            # The deny handler must match each method's cardinality:
            # a unary handler on a streaming method would wait for the
            # first inbound message instead of failing at call start.
            method = handler_call_details.method
            if method.endswith("/Chat"):
                return grpc.stream_stream_rpc_method_handler(deny)
            if method.endswith("/Stream"):
                return grpc.unary_stream_rpc_method_handler(deny)

            def deny_unary(request, context):
                context.abort(
                    grpc.StatusCode.UNAUTHENTICATED,
                    "missing or invalid authorization metadata "
                    "(expected: Bearer <cluster token>)",
                )

            return grpc.unary_unary_rpc_method_handler(deny_unary)

    return _AuthInterceptor()


def _effective_timeout(timeout, context):
    """Deadline propagation: the gRPC client's deadline caps the
    per-deployment timeout (reference: gRPCProxy honors request
    deadlines). time_remaining() is None when the client set none."""
    remaining = context.time_remaining()
    bounds = [t for t in (timeout, remaining) if t is not None]
    return min(bounds) if bounds else None


class GrpcIngressActor:
    """Deployed detached by :func:`ray_tpu.serve.api.start_grpc`.

    With ``require_auth=True`` every call must carry the cluster's
    shared-secret token as ``authorization: Bearer <token>`` metadata —
    the same token the control plane's RPC auth uses (config
    AUTH_TOKEN). Default off: like the HTTP proxy, the ingress is a
    public data plane unless the operator opts in.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        require_auth: bool = False,
    ):
        self._poller = RouteTablePoller()
        self._handles: dict = {}
        self._stream_handles: dict = {}
        self._port: int | None = None
        self._server = None
        self._require_auth = require_auth
        # Actor __init__ runs on the executor thread; the grpc.aio server
        # must live on the runtime loop where handle calls are native
        # (same pattern as proxy.ProxyActor.__init__).
        from ray_tpu import api as core_api

        asyncio.run_coroutine_threadsafe(
            self._start(host, port), core_api._runtime.loop
        ).result(timeout=30)

    async def _start(self, host: str, port: int):
        import grpc

        from ray_tpu.serve.protos import serve_pb2

        handlers = {
            "Call": grpc.unary_unary_rpc_method_handler(
                self._call,
                request_deserializer=serve_pb2.ServeRequest.FromString,
                response_serializer=serve_pb2.ServeReply.SerializeToString,
            ),
            "Stream": grpc.unary_stream_rpc_method_handler(
                self._stream,
                request_deserializer=serve_pb2.ServeRequest.FromString,
                response_serializer=serve_pb2.ServeReply.SerializeToString,
            ),
            "ListApplications": grpc.unary_unary_rpc_method_handler(
                self._list_applications,
                request_deserializer=(
                    serve_pb2.ListApplicationsRequest.FromString
                ),
                response_serializer=(
                    serve_pb2.ListApplicationsReply.SerializeToString
                ),
            ),
            "Chat": grpc.stream_stream_rpc_method_handler(
                self._chat,
                request_deserializer=serve_pb2.ServeRequest.FromString,
                response_serializer=serve_pb2.ServeReply.SerializeToString,
            ),
            "Healthz": grpc.unary_unary_rpc_method_handler(
                self._healthz,
                request_deserializer=serve_pb2.HealthzRequest.FromString,
                response_serializer=serve_pb2.HealthzReply.SerializeToString,
            ),
        }
        interceptors = []
        if self._require_auth:
            interceptors.append(_make_auth_interceptor())
        self._server = grpc.aio.server(interceptors=interceptors)
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
        )
        self._port = self._server.add_insecure_port(f"{host}:{port}")
        await self._server.start()

    def get_port(self) -> int:
        return self._port

    async def shutdown(self) -> bool:
        if self._server is not None:
            await self._server.stop(grace=1.0)
        return True

    # ---------------------------------------------------------- routing
    async def _resolve(self, request):
        """Map (application, deployment) onto a target deployment and
        per-deployment timeout via the controller route table."""
        await self._poller.refresh()
        app = request.application or "default"
        if app not in self._poller.by_app():
            # One forced refresh covers the just-deployed case.
            await self._poller.refresh(force=True)
        by_app = self._poller.by_app()
        if app not in by_app:
            return None, None, None
        ingress, timeout = by_app[app]
        deployment = request.deployment or ingress
        return app, deployment, timeout

    def _handle_for(self, app, deployment, method, stream):
        cache = self._stream_handles if stream else self._handles
        key = (app, deployment, method)
        handle = cache.get(key)
        if handle is None:
            handle = DeploymentHandle(
                deployment, app, method_name=method or "__call__",
                stream=stream,
            )
            cache[key] = handle
        return handle

    # --------------------------------------------------------- handlers
    async def _call(self, request, context):
        import grpc

        from ray_tpu.serve.protos import serve_pb2

        app, deployment, timeout = await self._resolve(request)
        if app is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"application {request.application or 'default'!r} not "
                "found; call ListApplications for the live set",
            )
        try:
            arg = _decode_payload(request)
        except ValueError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        handle = self._handle_for(
            app, deployment, request.method, stream=False
        )
        timeout = _effective_timeout(timeout, context)
        try:
            value = await asyncio.wait_for(
                handle.remote(arg), timeout=timeout
            )
        except asyncio.TimeoutError:
            await context.abort(
                grpc.StatusCode.DEADLINE_EXCEEDED,
                f"no reply within {timeout}s",
            )
        # tpulint: allow(broad-except reason=user-code failure becomes a gRPC INTERNAL status via context.abort — the error reaches the caller typed, not swallowed)
        except Exception as e:  # noqa: BLE001 - becomes a gRPC status
            await context.abort(
                grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}"
            )
        return _encode_reply(value, serve_pb2)

    async def _stream(self, request, context):
        import grpc

        from ray_tpu.serve.protos import serve_pb2

        app, deployment, timeout = await self._resolve(request)
        if app is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"application {request.application or 'default'!r} not "
                "found; call ListApplications for the live set",
            )
        try:
            arg = _decode_payload(request)
        except ValueError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        handle = self._handle_for(
            app, deployment, request.method, stream=True
        )
        timeout = _effective_timeout(timeout, context)
        agen = handle.remote(arg).__aiter__()
        while True:
            try:
                item = await asyncio.wait_for(
                    agen.__anext__(), timeout=timeout
                )
            except StopAsyncIteration:
                break
            except asyncio.TimeoutError:
                await context.abort(
                    grpc.StatusCode.DEADLINE_EXCEEDED,
                    f"no stream item within {timeout}s",
                )
            except grpc.aio.AbortError:
                raise
            # tpulint: allow(broad-except reason=stream failure becomes a gRPC INTERNAL status via context.abort — the error reaches the caller typed, not swallowed)
            except Exception as e:  # noqa: BLE001 - becomes a gRPC status
                await context.abort(
                    grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}"
                )
            yield _encode_reply(item, serve_pb2)

    async def _chat(self, request_iterator, context):
        """Bidi turn-based streaming: each inbound message invokes the
        deployment's STREAMING method; its items flow out before the
        next inbound message is consumed — the token-in/token-out shape
        LLM chat clients want. Routing fields are read per message, so
        one Chat connection can address several deployments."""
        import grpc

        from ray_tpu.serve.protos import serve_pb2

        async for request in request_iterator:
            app, deployment, timeout = await self._resolve(request)
            if app is None:
                await context.abort(
                    grpc.StatusCode.NOT_FOUND,
                    f"application {request.application or 'default'!r} "
                    "not found; call ListApplications for the live set",
                )
            try:
                arg = _decode_payload(request)
            except ValueError as e:
                await context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT, str(e)
                )
            handle = self._handle_for(
                app, deployment, request.method, stream=True
            )
            turn_timeout = _effective_timeout(timeout, context)
            agen = handle.remote(arg).__aiter__()
            while True:
                try:
                    item = await asyncio.wait_for(
                        agen.__anext__(), timeout=turn_timeout
                    )
                except StopAsyncIteration:
                    break
                except asyncio.TimeoutError:
                    await context.abort(
                        grpc.StatusCode.DEADLINE_EXCEEDED,
                        f"no stream item within {turn_timeout}s",
                    )
                except grpc.aio.AbortError:
                    raise
                # tpulint: allow(broad-except reason=turn failure becomes a gRPC INTERNAL status via context.abort — the error reaches the caller typed, not swallowed)
                except Exception as e:  # noqa: BLE001 - gRPC status
                    await context.abort(
                        grpc.StatusCode.INTERNAL,
                        f"{type(e).__name__}: {e}",
                    )
                yield _encode_reply(item, serve_pb2)

    async def _list_applications(self, request, context):
        from ray_tpu.serve.protos import serve_pb2

        await self._poller.refresh(force=True)
        apps = sorted(self._poller.by_app())
        return serve_pb2.ListApplicationsReply(application_names=apps)

    async def _healthz(self, request, context):
        from ray_tpu.serve.protos import serve_pb2

        return serve_pb2.HealthzReply(message="success")


# ------------------------------------------------------------- client


def _auth_metadata(token):
    return (("authorization", f"Bearer {token}"),) if token else None


def grpc_request(
    addr: str,
    *,
    application: str = "default",
    deployment: str = "",
    method: str = "",
    payload=None,
    timeout: float | None = 60.0,
    token: str | None = None,
):
    """Convenience unary client (tests / Python callers). Non-Python
    clients should consume ``protos/serve.proto`` directly. ``timeout``
    becomes the gRPC deadline, which the server propagates into its
    handle wait; ``token`` is sent as Bearer authorization metadata for
    ingresses started with require_auth."""
    import grpc

    from ray_tpu.serve.protos import serve_pb2

    with grpc.insecure_channel(addr) as channel:
        call = channel.unary_unary(
            f"/{SERVICE_NAME}/Call",
            request_serializer=serve_pb2.ServeRequest.SerializeToString,
            response_deserializer=serve_pb2.ServeReply.FromString,
        )
        req = _build_request(serve_pb2, application, deployment, method, payload)
        reply = call(req, timeout=timeout, metadata=_auth_metadata(token))
    return _decode_reply(reply)


def grpc_stream(
    addr: str,
    *,
    application: str = "default",
    deployment: str = "",
    method: str = "",
    payload=None,
    timeout: float | None = 60.0,
    token: str | None = None,
):
    """Server-streaming client: yields decoded items as they arrive."""
    import grpc

    from ray_tpu.serve.protos import serve_pb2

    with grpc.insecure_channel(addr) as channel:
        call = channel.unary_stream(
            f"/{SERVICE_NAME}/Stream",
            request_serializer=serve_pb2.ServeRequest.SerializeToString,
            response_deserializer=serve_pb2.ServeReply.FromString,
        )
        req = _build_request(serve_pb2, application, deployment, method, payload)
        for reply in call(
            req, timeout=timeout, metadata=_auth_metadata(token)
        ):
            yield _decode_reply(reply)


def grpc_chat(
    addr: str,
    payloads,
    *,
    application: str = "default",
    deployment: str = "",
    method: str = "",
    timeout: float | None = 60.0,
    token: str | None = None,
):
    """Bidi client for /Chat: sends each payload as one turn and yields
    every streamed reply item in order. The SERVER processes turns
    sequentially (a turn's stream completes before the next inbound
    message is consumed), so items arrive turn-by-turn — but gRPC's
    sender thread drains the request iterator ahead of replies, so this
    sync client cannot attribute items to turns; callers needing turn
    boundaries should encode them in the reply payloads."""
    import grpc

    from ray_tpu.serve.protos import serve_pb2

    def requests():
        for p in payloads:
            yield _build_request(
                serve_pb2, application, deployment, method, p
            )

    with grpc.insecure_channel(addr) as channel:
        call = channel.stream_stream(
            f"/{SERVICE_NAME}/Chat",
            request_serializer=serve_pb2.ServeRequest.SerializeToString,
            response_deserializer=serve_pb2.ServeReply.FromString,
        )
        for reply in call(
            requests(), timeout=timeout, metadata=_auth_metadata(token)
        ):
            yield _decode_reply(reply)


def _build_request(serve_pb2, application, deployment, method, payload):
    if isinstance(payload, bytes):
        body, ctype = payload, "bytes"
    elif isinstance(payload, str):
        body, ctype = payload.encode(), "text"
    else:
        body, ctype = json.dumps(payload).encode(), "json"
    return serve_pb2.ServeRequest(
        application=application,
        deployment=deployment,
        method=method,
        payload=body,
        content_type=ctype,
    )


def _decode_reply(reply):
    if reply.content_type == "bytes":
        return reply.payload
    if reply.content_type == "text":
        return reply.payload.decode()
    return json.loads(reply.payload.decode())
