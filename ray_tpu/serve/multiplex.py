"""@serve.multiplexed: many models per replica with LRU eviction.

(reference: python/ray/serve/multiplex.py _ModelMultiplexWrapper — a
replica lazily loads models by id, keeps up to max_num_models_per_replica
with LRU eviction; the router favors replicas with the model warm.)
"""

from __future__ import annotations

import collections
import functools
import inspect

from ray_tpu._private.sanitize import maybe_async_lock


def multiplexed(_fn=None, *, max_num_models_per_replica: int = 3):
    def deco(fn):
        if not inspect.iscoroutinefunction(fn):
            raise TypeError("@serve.multiplexed requires an async function")
        attr = f"__serve_mux_{fn.__name__}"

        @functools.wraps(fn)
        async def wrapper(self, model_id: str):
            state = getattr(self, attr, None)
            if state is None:
                state = {
                    "models": collections.OrderedDict(),
                    "locks": {},
                }
                setattr(self, attr, state)
            models = state["models"]
            if model_id in models:
                models.move_to_end(model_id)
                return models[model_id]
            # Instrumented under RAY_TPU_SANITIZE=1: the model-load
            # lock joins the sanitizer's global order graph, so an
            # inversion against any other serve/control-plane lock
            # raises at acquisition (TPU203's runtime twin).
            lock = state["locks"].setdefault(
                model_id, maybe_async_lock(
                    f"serve.multiplex.{fn.__name__}.{model_id}"))
            async with lock:
                if model_id in models:  # raced with another loader
                    models.move_to_end(model_id)
                    return models[model_id]
                while len(models) >= max_num_models_per_replica:
                    evicted_id, _evicted = models.popitem(last=False)
                    state["locks"].pop(evicted_id, None)
                model = await fn(self, model_id)
                models[model_id] = model
                return model

        return wrapper

    if _fn is not None:
        return deco(_fn)
    return deco
