"""serve public API: deployment / run / status / shutdown / proxy.

(reference: python/ray/serve/api.py — serve.deployment :246, serve.run
:686, serve.status, serve.delete, serve.shutdown; serve.start.)
"""

from __future__ import annotations

import logging
import time
from typing import Any

import ray_tpu
from ray_tpu.serve.config import DeploymentConfig
from ray_tpu.serve.controller import ServeController
from ray_tpu.serve.deployment import Application, Deployment
from ray_tpu.serve.handle import CONTROLLER_NAME, DeploymentHandle

logger = logging.getLogger("ray_tpu.serve")

PROXY_NAME = "_SERVE_PROXY"


def deployment(_func_or_class=None, **options) -> Deployment:
    """@serve.deployment / @serve.deployment(num_replicas=..., ...)."""

    def wrap(target):
        dep = Deployment(target, getattr(target, "__name__", "deployment"))
        if options:
            return dep.options(**options)
        return dep

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


def _get_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return None


def _get_or_create_controller():
    handle = _get_controller()
    if handle is not None:
        return handle
    controller = (
        ray_tpu.remote(ServeController)
        .options(
            name=CONTROLLER_NAME,
            lifetime="detached",
            max_concurrency=1000,
            num_cpus=0.1,
        )
        .remote()
    )
    # Fire-and-forget the reconciliation loop.
    controller.run_control_loop.remote()
    return controller


def run(
    app: Application,
    *,
    name: str = "default",
    route_prefix: str | None = None,
    _blocking: bool = True,
    timeout_s: float = 60.0,
) -> DeploymentHandle:
    """Deploy an application graph and return the ingress handle."""
    if not isinstance(app, Application):
        raise TypeError("serve.run takes an Application (deployment.bind())")
    controller = _get_or_create_controller()

    # Flatten the bind graph; de-dupe deployments by name; replace child
    # Application args with DeploymentHandles.
    nodes = list(app.walk())
    seen: dict[str, Application] = {}
    for node in nodes:
        prev = seen.get(node.deployment.name)
        if prev is not None and prev is not node:
            raise ValueError(
                f"duplicate deployment name {node.deployment.name!r} in app"
            )
        seen[node.deployment.name] = node

    def materialize(value: Any):
        if isinstance(value, Application):
            return DeploymentHandle(value.deployment.name, name)
        return value

    deployments = []
    for node in seen.values():
        deployments.append(
            {
                "name": node.deployment.name,
                "callable": node.deployment.func_or_class,
                "init_args": tuple(materialize(a) for a in node.bind_args),
                "init_kwargs": {
                    k: materialize(v) for k, v in node.bind_kwargs.items()
                },
                "config": node.deployment.config.to_dict(),
            }
        )
    if route_prefix is None:
        route_prefix = "/" if name == "default" else f"/{name}"
    spec = {
        "route_prefix": route_prefix,
        "ingress": app.deployment.name,
        "deployments": deployments,
    }
    ray_tpu.get(controller.deploy_application.remote(name, spec))

    if _blocking:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            st = ray_tpu.get(controller.get_status.remote()).get(name, {})
            if st and all(d["status"] == "HEALTHY" for d in st.values()):
                break
            time.sleep(0.1)
        else:
            raise TimeoutError(f"application {name!r} not healthy in time")
    return DeploymentHandle(app.deployment.name, name)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    controller = _get_controller()
    if controller is None:
        raise RuntimeError("serve is not running")
    status_map = ray_tpu.get(controller.get_status.remote())
    if name not in status_map:
        raise ValueError(f"no application named {name!r}")
    route_table = ray_tpu.get(controller.get_route_table.remote())
    for _route, (app, ingress, *_rest) in route_table.items():
        if app == name:
            return DeploymentHandle(ingress, name)
    raise ValueError(f"application {name!r} has no ingress")


def get_deployment_handle(
    deployment_name: str, app_name: str = "default"
) -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def status() -> dict:
    controller = _get_controller()
    if controller is None:
        return {}
    return ray_tpu.get(controller.get_status.remote())


def scale(
    deployment_name: str, target: int, app_name: str = "default"
) -> int:
    """Set a deployment's target replica count directly (operator/bench
    entry point). Scale-down retires victims through the drain protocol
    — they stop accepting, finish in-flight requests, then exit — so
    this never drops a request. For autoscaled deployments the value is
    clamped to [min_replicas, max_replicas] and the policy loop keeps
    adjusting from it. Returns the applied target."""
    controller = _get_controller()
    if controller is None:
        raise RuntimeError("serve is not running")
    return ray_tpu.get(
        controller.update_target.remote(app_name, deployment_name, target)
    )


def delete(name: str):
    controller = _get_controller()
    if controller is not None:
        ray_tpu.get(controller.delete_application.remote(name))


def shutdown():
    controller = _get_controller()
    if controller is not None:
        try:
            ray_tpu.get(controller.graceful_shutdown.remote(), timeout=10)
        except Exception:  # noqa: BLE001
            logger.debug(
                "graceful controller shutdown failed; killing it",
                exc_info=True,
            )
        ray_tpu.kill(controller)
    from ray_tpu.serve.grpc_ingress import GRPC_INGRESS_NAME

    for name in (PROXY_NAME, GRPC_INGRESS_NAME):
        try:
            ray_tpu.kill(ray_tpu.get_actor(name))
        except ValueError:
            pass
    # No deregistration wait is needed: kill synchronously marks the
    # actor DEAD at the head, and the head's get_actor treats DEAD as
    # not-found — a serve.run() issued right after shutdown() creates
    # a fresh controller instead of reviving the corpse.


def start_http(host: str = "127.0.0.1", port: int = 0) -> int:
    """Start the HTTP proxy actor; returns the bound port.

    (reference: per-node HTTPProxy actors, serve/_private/proxy.py:710 —
    here a single proxy actor is enough for one host.)"""
    from ray_tpu.serve.proxy import ProxyActor

    try:
        proxy = ray_tpu.get_actor(PROXY_NAME)
    except ValueError:
        proxy = (
            ray_tpu.remote(ProxyActor)
            .options(
                name=PROXY_NAME,
                lifetime="detached",
                max_concurrency=1000,
                num_cpus=0.1,
            )
            .remote(host, port)
        )
    return ray_tpu.get(proxy.get_port.remote())


def start_grpc(
    host: str = "127.0.0.1", port: int = 0, require_auth: bool = False
) -> int:
    """Start the gRPC ingress actor; returns the bound port.

    (reference: serve/_private/proxy.py:534 gRPCProxy — the reference
    serves gRPC next to HTTP; clients consume
    ray_tpu/serve/protos/serve.proto in any language.) With
    ``require_auth=True`` every non-Healthz call must carry the cluster
    token as ``authorization: Bearer <token>`` metadata."""
    from ray_tpu.serve.grpc_ingress import GRPC_INGRESS_NAME, GrpcIngressActor

    try:
        ingress = ray_tpu.get_actor(GRPC_INGRESS_NAME)
    except ValueError:
        ingress = (
            ray_tpu.remote(GrpcIngressActor)
            .options(
                name=GRPC_INGRESS_NAME,
                lifetime="detached",
                max_concurrency=1000,
                num_cpus=0.1,
            )
            .remote(host, port, require_auth)
        )
    return ray_tpu.get(ingress.get_port.remote())
