"""Per-request context inside replicas.

(reference: python/ray/serve/context.py _serve_request_context)
"""

from __future__ import annotations

import contextvars
from dataclasses import dataclass, field


@dataclass
class RequestContext:
    request_id: str = ""
    multiplexed_model_id: str = ""
    route: str = ""
    app_name: str = ""
    # Deployment this request was routed to (the bounded label serve
    # telemetry keys its histograms/gauges by).
    deployment: str = ""


_request_context: contextvars.ContextVar[RequestContext] = (
    contextvars.ContextVar("serve_request_context", default=RequestContext())
)


def get_request_context() -> RequestContext:
    return _request_context.get()


def set_request_context(ctx: RequestContext):
    return _request_context.set(ctx)


def get_multiplexed_model_id() -> str:
    """Model id the current request was routed with (reference:
    serve.get_multiplexed_model_id, python/ray/serve/api.py)."""
    return _request_context.get().multiplexed_model_id
