"""ray_tpu.serve: online model serving (controller / proxy / replica).

Capability-equivalent to the reference's Serve library (reference:
python/ray/serve/_private/controller.py:106 ServeController,
_private/replica.py:1139 Replica actors, handle.py:757 DeploymentHandle,
_private/proxy.py HTTP proxy, batching.py, multiplex.py), rebuilt on the
ray_tpu actor runtime:

- ``@serve.deployment`` declares a deployment; ``.bind()`` composes an
  application graph whose child deployments are injected as handles.
- ``serve.run(app)`` starts (or reuses) the controller actor, which
  reconciles target replica counts, restarts dead replicas, and runs the
  autoscaling loop.
- ``DeploymentHandle.remote`` routes with power-of-two-choices over
  client-tracked in-flight counts (reference: request_router/).
- ``serve.start_http`` launches an HTTP proxy actor that maps routes to
  application ingress handles.

TPU twist: replicas are ordinary ray_tpu actors, so a deployment can
reserve TPU chips per replica; a JAX model replica jits once in its
constructor and serves from device memory.
"""

from ray_tpu.serve.api import (
    delete,
    deployment,
    get_app_handle,
    get_deployment_handle,
    run,
    scale,
    shutdown,
    start_grpc,
    start_http,
    status,
)
from ray_tpu.serve.grpc_ingress import grpc_chat, grpc_request, grpc_stream
from ray_tpu.serve.batching import batch
from ray_tpu.serve.config import AutoscalingConfig
from ray_tpu.serve.context import get_multiplexed_model_id
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.multiplex import multiplexed
from ray_tpu.serve.rpc_ingress import RpcIngressActor, rpc_request

__all__ = [
    "AutoscalingConfig",
    "DeploymentHandle",
    "DeploymentResponse",
    "batch",
    "delete",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "get_multiplexed_model_id",
    "multiplexed",
    "RpcIngressActor",
    "grpc_chat",
    "grpc_request",
    "grpc_stream",
    "rpc_request",
    "run",
    "scale",
    "shutdown",
    "start_grpc",
    "start_http",
    "status",
]

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu('serve')
del _rlu
