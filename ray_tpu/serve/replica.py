"""Replica actor: hosts one copy of the user's deployment callable.

(reference: python/ray/serve/_private/replica.py:1139 `Replica` — wraps
the user callable, tracks ongoing requests for autoscaling stats, applies
user_config reconfiguration.)

Requests arrive as concurrent async actor calls (``handle_request`` is a
coroutine, so the core worker runs them out-of-order under
max_concurrency) — the replica itself enforces no queue; admission is the
router's job via in-flight caps.
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import inspect

from ray_tpu.serve.context import RequestContext, set_request_context


def _replica_scope(deployment_name: str, request_context: dict | None):
    """Span scope for one replica call: when the router shipped a trace
    context (serve telemetry on, ingress span upstream), run the user
    code under a ``serve:replica`` span parented to it — engine spans
    emitted inside (prefill/decode) then chain under this replica span.
    Returns (scope_cm, context_kwargs): the kwargs are the RequestContext
    fields with the transport-only "trace" key stripped."""
    ctx = dict(request_context or {})
    trace = ctx.pop("trace", None)
    if not trace:
        return contextlib.nullcontext(), ctx
    from ray_tpu.util import tracing

    return (
        tracing.linked_span(
            "serve:replica",
            parent=(trace[0], trace[1]),
            deployment=deployment_name,
            app=ctx.get("app_name", ""),
            request_id=ctx.get("request_id", ""),
        ),
        ctx,
    )


class ReplicaActor:
    def __init__(
        self,
        deployment_name: str,
        user_callable,  # class or function (cloudpickled by the runtime)
        init_args: tuple,
        init_kwargs: dict,
        user_config=None,
    ):
        self.deployment_name = deployment_name
        self._num_ongoing = 0
        self._num_served = 0
        self._draining = False
        if isinstance(user_callable, type):
            self._callable = user_callable(*init_args, **init_kwargs)
        else:
            self._callable = user_callable
        if user_config is not None:
            self._reconfigure(user_config)

    def _reconfigure(self, user_config):
        fn = getattr(self._callable, "reconfigure", None)
        if fn is None:
            raise ValueError(
                f"deployment {self.deployment_name} got user_config but "
                "defines no reconfigure() method"
            )
        fn(user_config)

    def reconfigure(self, user_config):
        self._reconfigure(user_config)
        return True

    def prepare_drain(self) -> int:
        """Scale-down retirement, step 1 (controller-driven): stop
        accepting new requests, keep serving in-flight ones. Returns
        the in-flight count so the controller can kill immediately when
        the replica is already idle. Idempotent."""
        self._draining = True
        return self._num_ongoing

    def _check_draining(self):
        """Admission gate: a draining replica refuses NEW requests with
        the typed error the router re-routes on. Routers holding a
        replica list from before the scale-down version bump race this
        window — the typed refusal (instead of a served request) is
        what makes the drain a hard barrier."""
        if self._draining:
            from ray_tpu.exceptions import ReplicaDrainingError

            raise ReplicaDrainingError(self.deployment_name)

    async def handle_request(
        self,
        method_name: str,
        request_args: tuple,
        request_kwargs: dict,
        request_context: dict | None = None,
    ):
        self._check_draining()
        self._num_ongoing += 1
        scope, ctx_kwargs = _replica_scope(
            self.deployment_name, request_context
        )
        try:
            with scope:
                set_request_context(RequestContext(**ctx_kwargs))
                if inspect.isfunction(self._callable):
                    fn = self._callable  # function deployment
                else:
                    fn = getattr(self._callable, method_name)
                if inspect.iscoroutinefunction(fn):
                    return await fn(*request_args, **request_kwargs)
                # Run sync user code off the event loop, propagating the
                # request contextvars into the executor thread.
                ctx = contextvars.copy_context()
                loop = asyncio.get_running_loop()
                return await loop.run_in_executor(
                    None,
                    lambda: ctx.run(fn, *request_args, **request_kwargs),
                )
        finally:
            self._num_ongoing -= 1
            self._num_served += 1

    async def handle_request_streaming(
        self,
        method_name: str,
        request_args: tuple,
        request_kwargs: dict,
        request_context: dict | None = None,
    ):
        """Streaming twin of handle_request (reference: replica.py
        `handle_request_streaming` — user generators stream through
        ObjectRefGenerator). Yields the user method's items as they are
        produced; a non-generator result yields exactly once, so the
        router can use one call shape for both."""
        self._check_draining()
        self._num_ongoing += 1
        scope, ctx_kwargs = _replica_scope(
            self.deployment_name, request_context
        )
        try:
            with scope:
                set_request_context(RequestContext(**ctx_kwargs))
                if inspect.isfunction(self._callable):
                    fn = self._callable
                else:
                    fn = getattr(self._callable, method_name)
                if inspect.isasyncgenfunction(fn):
                    result = fn(*request_args, **request_kwargs)
                elif inspect.iscoroutinefunction(fn):
                    result = await fn(*request_args, **request_kwargs)
                else:
                    ctx = contextvars.copy_context()
                    loop = asyncio.get_running_loop()
                    result = await loop.run_in_executor(
                        None,
                        lambda: ctx.run(fn, *request_args, **request_kwargs),
                    )
                if inspect.isasyncgen(result):
                    async for item in result:
                        yield item
                elif inspect.isgenerator(result):
                    # Drive sync generators off-loop so user compute
                    # between yields doesn't stall this replica's other
                    # requests.
                    loop = asyncio.get_running_loop()
                    _done = object()
                    while True:
                        item = await loop.run_in_executor(
                            None, lambda: next(result, _done)
                        )
                        if item is _done:
                            break
                        yield item
                else:
                    yield result
        finally:
            self._num_ongoing -= 1
            self._num_served += 1

    def get_stats(self) -> dict:
        import os

        return {
            "num_ongoing_requests": self._num_ongoing,
            "num_served": self._num_served,
            "draining": self._draining,
            # The hosting worker's pid: the deterministic handle the
            # replica-SIGKILL chaos path (test_utils.kill_one_replica)
            # and bench_serve's kill leg grab a victim by.
            "pid": os.getpid(),
        }

    def check_health(self) -> bool:
        fn = getattr(self._callable, "check_health", None)
        if fn is not None:
            fn()
        return True
