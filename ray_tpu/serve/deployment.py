"""@serve.deployment decorator and application graphs.

(reference: python/ray/serve/deployment.py Deployment / Application —
``.bind()`` builds a composition graph; serve.run deploys the whole
graph, injecting DeploymentHandles for bound children.)
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any

from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig


@dataclass
class Deployment:
    func_or_class: Any
    name: str
    config: DeploymentConfig = field(default_factory=DeploymentConfig)

    def options(
        self,
        *,
        name: str | None = None,
        num_replicas: int | None = None,
        max_ongoing_requests: int | None = None,
        request_timeout_s: float | None = None,
        drain_timeout_s: float | None = None,
        autoscaling_config: AutoscalingConfig | dict | None = None,
        ray_actor_options: dict | None = None,
        user_config: dict | None = None,
    ) -> "Deployment":
        cfg = replace(self.config)
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if request_timeout_s is not None:
            if request_timeout_s <= 0:
                raise ValueError("request_timeout_s must be positive")
            cfg.request_timeout_s = request_timeout_s
        if drain_timeout_s is not None:
            if drain_timeout_s < 0:
                raise ValueError("drain_timeout_s must be >= 0")
            cfg.drain_timeout_s = drain_timeout_s
        if autoscaling_config is not None:
            if isinstance(autoscaling_config, dict):
                autoscaling_config = AutoscalingConfig(**autoscaling_config)
            cfg.autoscaling_config = autoscaling_config
        if ray_actor_options is not None:
            cfg.ray_actor_options = dict(ray_actor_options)
        if user_config is not None:
            cfg.user_config = user_config
        return Deployment(self.func_or_class, name or self.name, cfg)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __call__(self, *a, **kw):
        raise TypeError(
            f"deployment {self.name} cannot be called directly; "
            "deploy it with serve.run(<dep>.bind(...))"
        )


@dataclass
class Application:
    """A node in the bind graph; child Applications in the init args
    become DeploymentHandles at deploy time."""

    deployment: Deployment
    bind_args: tuple
    bind_kwargs: dict

    def walk(self):
        """Yield this node and all descendants (depth-first)."""
        yield self
        for a in list(self.bind_args) + list(self.bind_kwargs.values()):
            if isinstance(a, Application):
                yield from a.walk()
