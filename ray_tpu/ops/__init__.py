"""TPU-friendly model ops: norms, rotary embeddings, attention.

All ops are shape-static, bf16-matmul-first, and written so XLA can fuse
the elementwise work into the surrounding matmuls (MXU-friendly). Pallas
kernels, where present, are optional fast paths with XLA fallbacks so the
same code runs on the CPU test mesh.
"""

from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.rope import apply_rope, rope_frequencies
from ray_tpu.ops.attention import causal_attention

__all__ = ["rms_norm", "apply_rope", "rope_frequencies", "causal_attention"]
