"""Causal multi-head attention (GQA-aware).

Default path is pure XLA: einsum → fp32 softmax → einsum, which XLA tiles
onto the MXU and fuses the masking/softmax elementwise work into. A Pallas
flash-attention kernel (ray_tpu.ops.pallas.flash_attention) is used on TPU
for long sequences when available; this module picks the path.

Replaces nothing in the reference directly — the reference has no attention
op (SURVEY.md section 5, long-context row: "Not present") — but is the
compute core under ray_tpu.models and the ring-attention SP op.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -2.0e38


def _repeat_kv(k: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[B, S, Hkv, D] → [B, S, Hkv*n_rep, D] for grouped-query attention."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d
    )


def causal_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    q_offset: jnp.ndarray | int = 0,
    kv_offset: jnp.ndarray | int = 0,
) -> jnp.ndarray:
    """Causal attention over [B, S, H, D] tensors; supports GQA (Hkv | H).

    ``q_offset``/``kv_offset`` shift the absolute positions of the query and
    key blocks — used by ring attention, where each SP shard holds a
    different slice of the sequence.
    """
    n_heads = q.shape[2]
    n_kv = k.shape[2]
    if n_heads % n_kv:
        raise ValueError(f"n_heads={n_heads} not divisible by n_kv={n_kv}")
    k = _repeat_kv(k, n_heads // n_kv)
    v = _repeat_kv(v, n_heads // n_kv)

    scale = q.shape[-1] ** -0.5
    # [B, H, Sq, Sk]
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    )
    logits = logits * scale

    q_pos = jnp.arange(q.shape[1]) + q_offset
    k_pos = jnp.arange(k.shape[1]) + kv_offset
    mask = (q_pos[:, None] >= k_pos[None, :])[None, None, :, :]
    logits = jnp.where(mask, logits, _NEG_INF)

    # A query row with no visible keys (routine in ring attention: a
    # shard's whole KV block can be in the query's future) must produce
    # 0, not mean(V). Softmax of the all-_NEG_INF row is uniform, so
    # multiply by row validity — for a causal mask a row is fully masked
    # iff q_pos < min(k_pos) = kv_offset, a [Sq] predicate that keeps
    # XLA's fused softmax intact.
    probs = jax.nn.softmax(logits, axis=-1)
    row_valid = (q_pos >= kv_offset).astype(probs.dtype)
    probs = (probs * row_valid[None, None, :, None]).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
