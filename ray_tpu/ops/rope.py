"""Rotary position embeddings (RoPE), precomputed frequencies + fused apply."""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(
    head_dim: int, max_seq: int, theta: float = 500000.0
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Return (cos, sin) tables of shape [max_seq, head_dim // 2], fp32."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    pos = jnp.arange(max_seq, dtype=jnp.float32)
    angles = jnp.outer(pos, inv_freq)  # [S, D/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(
    x: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    positions: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Rotate ``x`` of shape [..., S, H, D] by position.

    ``cos``/``sin`` are [max_seq, D/2]; ``positions`` (optional, [..., S])
    selects rows, defaulting to arange(S). Split-halves convention.
    """
    seq = x.shape[-3]
    if positions is None:
        c = cos[:seq]
        s = sin[:seq]
        # broadcast over batch and heads: [S, 1, D/2]
        c = c[:, None, :]
        s = s[:, None, :]
    else:
        c = cos[positions][..., :, None, :]
        s = sin[positions][..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)
