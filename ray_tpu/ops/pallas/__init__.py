"""Pallas TPU kernels for the hot ops (SURVEY.md §7: "pallas kernels for
the hot ops"). CPU tests run these with interpret=True."""

from ray_tpu.ops.pallas.flash_attention import flash_attention

__all__ = ["flash_attention"]
