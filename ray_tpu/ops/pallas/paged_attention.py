"""Paged decode attention as a Pallas TPU kernel.

The XLA fallback in ``llm/paged_kv.py`` gathers every slot's full page
window out of the pool (``jnp.take``) and then repeats KV to all query
heads — per step it moves B x window x n_heads x Dh bytes of HBM
regardless of each request's true length. This kernel removes both
factors:

- **Pages are read in place.** The grid is (B, max_pages) and the K/V
  BlockSpec index maps use the scalar-prefetched block table to point
  each grid step at the physical page — no gathered copy of the window
  ever exists in HBM.
- **GQA-aware blocking.** Queries are laid out [B, Hkv, n_rep*K, Dh] so
  each page's K/V block ([P, Hkv, Dh]) is multiplied once per KV head
  against its whole query group — KV is never repeated to n_heads.
  Traffic scales with n_kv_heads (8 for llama-8B), not n_heads (32).
- **Per-slot length early-exit.** Pages past a slot's true length are
  clamped by the index map to the slot's LAST page: Pallas skips the
  DMA when consecutive grid steps map to the same block, and pl.when
  skips the compute, so a 100-token request in a 4096-token-wide table
  pays for one page, not 32.

Numerics follow the flash kernel (online softmax with finite mask
values, fp32 accumulation); outputs match the XLA gather path to fp
tolerance, and greedy token streams are identical (gated by tests).

The pool layout is HEAD-major ([pages, Hkv, P, Dh]): each KV head's
page tile is a contiguous slice, measured ~40% faster than page-major
for the kernel. NOTE the honest caveat: the same round also rewrote
the XLA gather fallback (einsum-folded, GQA-grouped, no repeat) which
brought IT from 17.4 ms to ~4.6 ms at 32/8 heads — at this window
size the kernel's remaining edge is 1.1-1.3x, and its structural
advantage (no materialized gathered window) grows with table width.
Grouping multiple pages per grid step measured SLOWER (see
pages_per_step below).

The reference has no paged attention of its own — ray.llm buys it from
vLLM (reference: python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_models.py:234, engine_kwargs pass-through); this is the TPU-native
equivalent of vLLM's paged_attention kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Finite mask/init values (see flash_attention.py): exp(x - m) underflows
# to exactly 0 without the -inf NaN guards.
_MASK = -1e9
_M_INIT = -1e30
_LANES = 128


def _make_kernel(
    group: int, page_size: int, n_queries: int, scale: float
):
    """Kernel over GROUPS of ``group`` pages per grid step: fewer,
    fatter steps amortize per-step overhead and let Pallas issue the
    group's page DMAs together. Refs: scalar prefetch (tables, lastp,
    pos), q, group x k pages, group x v pages, out, then m/l/acc
    scratch."""

    def _kernel(tables_ref, lastp_ref, pos_ref, q_ref, *rest):
        k_refs = rest[:group]  # each [1, Hkv, P, Dh]
        v_refs = rest[group: 2 * group]
        o_ref = rest[2 * group]  # [1, Hkv, R, Dh]
        m_ref, l_ref, acc_ref = rest[2 * group + 1:]
        b = pl.program_id(0)
        i = pl.program_id(1)

        @pl.when(i == 0)
        def _init():
            m_ref[...] = jnp.full_like(m_ref, _M_INIT)
            l_ref[...] = jnp.zeros_like(l_ref)
            acc_ref[...] = jnp.zeros_like(acc_ref)

        n_kv = q_ref.shape[1]
        for j in range(group):
            # Global page index of this group member; members past the
            # slot's last page skip compute (their block index was
            # clamped, so no DMA happened either).
            ip = i * group + j

            @pl.when(ip <= lastp_ref[b])
            def _accumulate(j=j, ip=ip):
                k_ref, v_ref = k_refs[j], v_refs[j]
                # Static unrolled loop over KV heads: Mosaic wants
                # plain 2D MXU matmuls, and the head-major layout makes
                # each head's [P, Dh] tile a contiguous slice. Each
                # group's K/V tile is touched once for all n_rep * K
                # query rows — KV is never repeated across the group.
                for g in range(n_kv):
                    s = jax.lax.dot_general(
                        q_ref[0, g], k_ref[0, g],
                        dimension_numbers=(((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    ) * scale  # [R, P]
                    # Causal / length mask: key cell c lives at global
                    # position ip*P + c; query row r is query token
                    # r % K writing at pos + r % K. (Stale cells beyond
                    # the frontier are masked; cells behind it are
                    # valid by the scatter-before-gather invariant
                    # shared with the XLA path.)
                    key_pos = ip * page_size + jax.lax.broadcasted_iota(
                        jnp.int32, s.shape, 1
                    )
                    q_pos = pos_ref[b] + jax.lax.broadcasted_iota(
                        jnp.int32, s.shape, 0
                    ) % n_queries
                    s = jnp.where(key_pos > q_pos, _MASK, s)

                    m_prev = m_ref[g, :, 0]  # [R]
                    l_prev = l_ref[g, :, 0]
                    m_new = jnp.maximum(m_prev, s.max(axis=-1))
                    p = jnp.exp(s - m_new[:, None])  # masked -> 0
                    alpha = jnp.exp(m_prev - m_new)
                    l_ref[g] = jnp.broadcast_to(
                        (alpha * l_prev + p.sum(axis=-1))[:, None],
                        l_ref.shape[1:],
                    )
                    m_ref[g] = jnp.broadcast_to(
                        m_new[:, None], m_ref.shape[1:]
                    )
                    acc_ref[g] = acc_ref[g] * alpha[:, None] + (
                        jax.lax.dot_general(
                            p.astype(v_ref.dtype), v_ref[0, g],
                            dimension_numbers=(((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32,
                        )
                    )

        @pl.when(i == pl.num_programs(1) - 1)
        def _finalize():
            l = l_ref[:, :, 0]
            denom = jnp.where(l == 0.0, 1.0, l)
            o_ref[0] = (
                acc_ref[...] / denom[:, :, None]
            ).astype(o_ref.dtype)

    return _kernel


@functools.partial(
    jax.jit, static_argnames=("n_kv_heads", "interpret", "pages_per_step")
)
def paged_attention(
    q: jnp.ndarray,  # [B, K, H, Dh] (rope applied)
    k_pool: jnp.ndarray,  # [num_pages, Hkv, P, Dh] (head-major)
    v_pool: jnp.ndarray,  # [num_pages, Hkv, P, Dh]
    block_tables: jnp.ndarray,  # [B, max_pages] int32 (-1 = unused)
    positions: jnp.ndarray,  # [B] int32: write position of q[:, 0]
    *,
    n_kv_heads: int,
    interpret: bool = False,
    pages_per_step: int = 1,
) -> jnp.ndarray:
    """Decode/verify attention over the page pool; returns [B, K, H, Dh].

    Query token k of slot b attends to key positions <= positions[b]+k
    within the slot's block table (the K=1 case is plain decode). The
    pool is read page-by-page in place — see module docstring.
    """
    b, kk, n_heads, head_dim = q.shape
    num_pages, hkv, page_size, _ = k_pool.shape
    assert hkv == n_kv_heads
    n_rep = n_heads // n_kv_heads
    r = n_rep * kk
    max_pages = block_tables.shape[1]

    # [B, K, H, Dh] -> [B, Hkv, n_rep*K, Dh]: head h = g*n_rep + h_rep
    # lands in group g, row h_rep*K + k — so row % K is the query index.
    qg = (
        q.transpose(0, 2, 1, 3)
        .reshape(b, n_kv_heads, n_rep, kk, head_dim)
        .reshape(b, n_kv_heads, r, head_dim)
    )
    tables = jnp.maximum(block_tables, 0).astype(jnp.int32)
    lastp = jnp.clip(
        (positions + kk - 1) // page_size, 0, max_pages - 1
    ).astype(jnp.int32)
    # pages_per_step > 1 loads a GROUP of pages per grid step. Measured
    # on v5e at batch 64: G=1 3.4 ms, G=4 5.0 ms, G=8 3.5 ms — the
    # extra per-spec double buffers cost more VMEM/pipelining than the
    # step amortization saves, so 1 is the default; the knob stays for
    # other table-width/page-size regimes.
    group = pages_per_step
    while max_pages % group:
        group //= 2  # table widths are powers of two in practice
    group = max(group, 1)

    def page_spec(j):
        # Group member j of grid step i holds page i*group + j, clamped
        # to the slot's last live page: steps past it re-map to the
        # same block index and Pallas elides the repeated DMA, so the
        # table's dead width costs no HBM traffic.
        return pl.BlockSpec(
            (1, n_kv_heads, page_size, head_dim),
            lambda bi, i, tab, lp, pos, j=j: (
                tab[bi, jnp.minimum(i * group + j, lp[bi])], 0, 0, 0,
            ),
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, max_pages // group),
        in_specs=[
            pl.BlockSpec(
                (1, n_kv_heads, r, head_dim),
                lambda bi, i, tab, lp, pos: (bi, 0, 0, 0),
            ),
            *[page_spec(j) for j in range(group)],  # K pages
            *[page_spec(j) for j in range(group)],  # V pages
        ],
        out_specs=pl.BlockSpec(
            (1, n_kv_heads, r, head_dim),
            lambda bi, i, tab, lp, pos: (bi, 0, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((n_kv_heads, r, _LANES), jnp.float32),
            pltpu.VMEM((n_kv_heads, r, _LANES), jnp.float32),
            pltpu.VMEM((n_kv_heads, r, head_dim), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        _make_kernel(
            group=group,
            page_size=page_size,
            n_queries=kk,
            scale=head_dim**-0.5,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (b, n_kv_heads, r, head_dim), q.dtype
        ),
        interpret=interpret,
    )(
        tables, lastp, positions.astype(jnp.int32), qg,
        *([k_pool] * group), *([v_pool] * group),
    )
    # [B, Hkv, n_rep*K, Dh] -> [B, K, H, Dh]
    return (
        out.reshape(b, n_kv_heads, n_rep, kk, head_dim)
        .transpose(0, 3, 1, 2, 4)
        .reshape(b, kk, n_heads, head_dim)
    )
