"""Flash attention as a Pallas TPU kernel.

Online-softmax attention (Dao et al.) tiled for the MXU: the kernel never
materializes the [S, S] score matrix — each (q-block, kv-block) grid step
rescales a running (max, denom, acc) triple held in VMEM scratch, which
persists across the innermost (sequential) grid dimension on TPU. Causal
blocks strictly above the diagonal are skipped entirely, halving the work.

The reference has no attention kernels at all (SURVEY.md §5 long-context
row: delegated to vLLM/user code); this is native.

Layout: [B, S, H, D] (the model's convention). GQA is handled by index
mapping: q head h reads kv head h // (H // Hkv) — no materialized repeat.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")
_LANES = 128  # TPU vector lane count: scratch stats are lane-replicated


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
    *, block_q: int, block_kv: int, num_kv: int, scale: float, causal: bool,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # Causal: a kv block strictly above the diagonal contributes nothing.
    first_masked = (qi + 1) * block_q  # kv positions >= this are masked
    run = jnp.logical_or(
        not causal, ki * block_kv < first_masked
    )

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [block_q, D]
        k = k_ref[0].astype(jnp.float32)  # [block_kv, D]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_kv]

        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            kv_pos = ki * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1
            )
            s = jnp.where(kv_pos > q_pos, _NEG_INF, s)

        m_prev = m_ref[:, 0]  # [block_q]
        l_prev = l_ref[:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        # All-masked rows keep m == -inf; exp(-inf - -inf) would be NaN.
        safe_m = jnp.where(m_new == _NEG_INF, 0.0, m_new)
        p = jnp.exp(s - safe_m[:, None])
        p = jnp.where(s == _NEG_INF, 0.0, p)
        alpha = jnp.where(
            m_prev == _NEG_INF, 0.0, jnp.exp(m_prev - safe_m)
        )
        l_new = alpha * l_prev + p.sum(axis=-1)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(ki == num_kv - 1)
    def _finalize():
        l = l_ref[:, 0]
        denom = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows → 0 output
        o_ref[0] = (acc_ref[...] / denom[:, None]).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_kv", "interpret", "scale"),
)
def flash_attention(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,  # [B, S, Hkv, D]
    v: jnp.ndarray,  # [B, S, Hkv, D]
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int = 256,
    block_kv: int = 512,
    interpret: bool = False,
) -> jnp.ndarray:
    b, s, h, d = q.shape
    hkv = k.shape[2]
    if h % hkv:
        raise ValueError(f"n_heads={h} not divisible by n_kv={hkv}")
    n_rep = h // hkv
    block_q = min(block_q, s)
    block_kv = min(block_kv, s)
    if s % block_q or s % block_kv:
        raise ValueError(f"seq {s} not divisible by blocks {block_q}/{block_kv}")
    if scale is None:
        scale = d**-0.5
    num_q, num_kv = s // block_q, s // block_kv

    # [B, S, H, D] → [B*H, S, D]
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)

    kernel = functools.partial(
        _flash_kernel,
        block_q=block_q,
        block_kv=block_kv,
        num_kv=num_kv,
        scale=scale,
        causal=causal,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec(
                (1, block_kv, d),
                lambda bh, qi, ki, n_rep=n_rep: (bh // n_rep, ki, 0),
            ),
            pl.BlockSpec(
                (1, block_kv, d),
                lambda bh, qi, ki, n_rep=n_rep: (bh // n_rep, ki, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running denom
            pltpu.VMEM((block_q, d), jnp.float32),  # output accumulator
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
