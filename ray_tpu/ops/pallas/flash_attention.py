"""Flash attention as Pallas TPU kernels — forward AND backward.

Online-softmax attention (Dao et al.) tiled for the MXU: the forward
never materializes the [S, S] score matrix — each (q-block, kv-block)
grid step rescales a running (max, denom, acc) triple held in VMEM
scratch, which persists across the innermost (sequential) grid dimension
on TPU. Causal blocks strictly above the diagonal are skipped entirely,
halving the work. The forward also emits the per-row logsumexp so the
backward can recompute probabilities blockwise (flash-2 style): dq
accumulates over kv blocks, dk/dv over q blocks, all O(S) memory.

The reference has no attention kernels at all (SURVEY.md §5 long-context
row: delegated to vLLM/user code); this is native.

Layout: [B, S, H, D] (the model's convention). GQA in the forward is
handled by index mapping (q head h reads kv head h // n_rep — no
materialized repeat); the backward expands kv to H heads and sums
dk/dv over each group's n_rep q heads afterwards.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu._private.jax_compat import shard_map

_NEG_INF = float("-inf")
# Finite mask value: exp(_MASK - m) underflows to exactly 0 for any
# finite row max m, so masked positions need NO NaN-guard `where` passes
# (with -inf they would: exp(-inf - -inf) = NaN). Kept well inside fp32
# range so (s - m) cannot overflow.
_MASK = -1e9
# Running-max initializer: any real score beats it, and exp(_M_INIT - m)
# underflows to 0 (the first block's rescale factor) without -inf NaNs.
_M_INIT = -1e30
_LANES = 128  # TPU vector lane count: scratch stats are lane-replicated
# Budget for the backward's whole-head dq VMEM slab (S·d·4 bytes); past
# this the kernel switches to HBM fp32 partials (see _bwd_kernel).
_DQ_SLAB_VMEM_BYTES = 4 * 1024 * 1024


# --------------------------------------------------------------- forward
def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
    *, block_q: int, block_kv: int, num_kv: int, causal: bool,
):
    """q is PRE-SCALED by the caller (one cheap [S, D] pass instead of a
    per-block [block_q, block_kv] multiply). Elementwise work is the VPU
    bottleneck at D=64, so the softmax path is kept to the minimum
    passes: masking runs ONLY on blocks the diagonal crosses, and the
    finite _MASK/_M_INIT values make every NaN-guard `where` unnecessary.
    """
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _M_INIT)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _accumulate(masked: bool):
        # Inputs stay in their storage dtype (bf16): the MXU multiplies
        # bf16 at full rate and accumulates fp32 via
        # preferred_element_type — upcasting first would waste VPU
        # passes on [block, D] casts.
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_kv] fp32
        if masked:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            kv_pos = ki * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1
            )
            s = jnp.where(kv_pos > q_pos, _MASK, s)
        m_prev = m_ref[:, 0]  # [block_q]
        l_prev = l_ref[:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])  # masked entries underflow to 0
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + p.sum(axis=-1)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot(
            p.astype(v_ref.dtype), v_ref[0],
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    if causal:
        # Block classes: fully above the diagonal → skip; crossed by the
        # diagonal → masked softmax; fully below → unmasked (most blocks
        # at long seq, saving the iota+compare+select passes).
        crossed = jnp.logical_and(
            ki * block_kv < (qi + 1) * block_q,
            (ki + 1) * block_kv - 1 > qi * block_q,
        )
        below = (ki + 1) * block_kv - 1 <= qi * block_q

        @pl.when(crossed)
        def _masked():
            _accumulate(True)

        @pl.when(below)
        def _unmasked():
            _accumulate(False)
    else:
        _accumulate(False)

    @pl.when(ki == num_kv - 1)
    def _finalize():
        l = l_ref[:, 0]
        m = m_ref[:, 0]
        denom = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows → 0 output
        o_ref[0] = (acc_ref[...] / denom[:, None]).astype(o_ref.dtype)
        # logsumexp per row, consumed by the backward kernels.
        lse = jnp.where(l == 0.0, _NEG_INF, m + jnp.log(denom))
        lse_ref[0, 0] = lse.astype(jnp.float32)


def _fwd_call(qr, kr, vr, n_rep, causal, block_q, block_kv, interpret):
    bh, s, d = qr.shape
    num_q, num_kv = s // block_q, s // block_kv
    kernel = functools.partial(
        _fwd_kernel,
        block_q=block_q, block_kv=block_kv, num_kv=num_kv, causal=causal,
    )
    return pl.pallas_call(
        kernel,
        grid=(bh, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec(
                (1, block_kv, d),
                lambda b, qi, ki, n_rep=n_rep: (b // n_rep, ki, 0),
            ),
            pl.BlockSpec(
                (1, block_kv, d),
                lambda b, qi, ki, n_rep=n_rep: (b // n_rep, ki, 0),
            ),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            # [BH, 1, S]: a (1, 1, block_q) block satisfies the TPU
            # (8, 128) tile rule (middle dim equals the array dim).
            pl.BlockSpec((1, 1, block_q), lambda b, qi, ki: (b, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), qr.dtype),
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running denom
            pltpu.VMEM((block_q, d), jnp.float32),  # output accumulator
        ],
        interpret=interpret,
    )(qr, kr, vr)


# -------------------------------------------------------------- backward
def _recompute_p(q_ref, k_ref, lse_ref, qi, ki, block_q, block_kv, masked):
    """Blockwise softmax recompute from the saved lse. q is pre-scaled
    (see _fwd_kernel); masked entries underflow to exactly 0, so no
    guard passes are needed."""
    s = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if masked:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        kv_pos = ki * block_kv + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        s = jnp.where(kv_pos > q_pos, _MASK, s)
    lse = lse_ref[0, 0]  # [block_q]; finite for every computed row
    return jnp.exp(s - lse[:, None])


def _bwd_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref, dk_ref, dv_ref, dq_acc, dk_acc, dv_acc,
    *, block_q: int, block_kv: int, num_q: int, num_kv: int, scale: float,
    causal: bool, dq_slab: bool,
):
    """One-pass fused backward: each (kv, q) block pair recomputes p ONCE
    and feeds all three gradients — vs the previous two-kernel backward
    this drops 2 of 7 per-pair MXU passes (the duplicated qk^T and
    do·v^T) and one exp recompute. dk/dv accumulate in [block_kv, d]
    scratch across the inner q sweep. dq has two modes:

    - dq_slab=True (short/medium seq): dq accumulates in a FULL [S, d]
      fp32 VMEM slab (1 MB at S=2048·d=128) persisting across the whole
      kv sweep of one head — no HBM partials exist.
    - dq_slab=False (long seq, slab would blow VMEM): each (kv, q) pair
      writes its fp32 dq contribution to a [num_kv, BH, S, d] partials
      output (every block written exactly once — the expanded-output
      pattern of the public splash kernels) and the caller sums over
      the leading axis."""
    ki = pl.program_id(1)  # kv outer, q inner
    qi = pl.program_id(2)
    q_slice = pl.ds(qi * block_q, block_q)

    @pl.when(qi == 0)
    def _init_kv():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    if dq_slab:
        @pl.when(ki == 0)
        def _init_dq():
            # ki==0 visits every q block (the first kv block is never
            # causal-skipped), so each slice zeroes exactly once a head.
            dq_acc[q_slice, :] = jnp.zeros(
                (block_q, dq_acc.shape[1]), jnp.float32
            )

    def _compute(masked: bool):
        p = _recompute_p(
            q_ref, k_ref, lse_ref, qi, ki, block_q, block_kv, masked
        )
        do = do_ref[0]
        # dv += p^T @ do — p downcast to the MXU dtype (flash-standard)
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0, 0][:, None])
        # dq contribution: ds @ k (q is pre-scaled; the chain-rule scale
        # lands once — at slab write-out, or per partial here).
        contrib = jax.lax.dot(
            ds.astype(k_ref.dtype), k_ref[0],
            preferred_element_type=jnp.float32,
        )
        if dq_slab:
            dq_acc[q_slice, :] += contrib
        else:
            dq_ref[0, 0] = contrib * scale
        # dk += ds^T @ q_scaled — exactly scale·dsᵀ@q, the chain-rule
        # factor rides the pre-scaled q.
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # q blocks entirely before this kv block see none of it.
        overlaps = (qi + 1) * block_q > ki * block_kv
        crossed = jnp.logical_and(
            overlaps, (ki + 1) * block_kv - 1 > qi * block_q
        )
        below = jnp.logical_and(
            overlaps, (ki + 1) * block_kv - 1 <= qi * block_q
        )

        @pl.when(crossed)
        def _masked():
            _compute(True)

        @pl.when(below)
        def _unmasked():
            _compute(False)

        if not dq_slab:
            # Skipped pairs still own a partials block; the output
            # window holds stale VMEM unless written.
            @pl.when(jnp.logical_not(overlaps))
            def _skipped():
                dq_ref[0, 0] = jnp.zeros_like(dq_ref[0, 0])
    else:
        _compute(False)

    if dq_slab:
        # The dq output block (indexed by qi) is flushed at every visit;
        # only the final kv sweep's value survives, with the full sum.
        dq_ref[0] = (dq_acc[q_slice, :] * scale).astype(dq_ref.dtype)

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_impl(q, k, v, causal, scale, block_q, block_kv, interpret):
    b, s, h, d = q.shape
    hkv = k.shape[2]
    n_rep = h // hkv
    # Pre-scale q once (fused into the transpose by XLA) instead of a
    # per-block [block_q, block_kv] multiply inside the kernel. Costs
    # one bf16 rounding of q when scale is not a power of two (d=128 →
    # 2^-3.5) — the standard flash-kernel tradeoff.
    qs = (q.astype(jnp.float32) * scale).astype(q.dtype)
    qr = qs.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    out, lse = _fwd_call(
        qr, kr, vr, n_rep, causal, block_q, block_kv, interpret
    )
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash(
    q, k, v, causal, scale, block_q, block_kv,
    bwd_block_q, bwd_block_kv, interpret,
):
    out, _ = _flash_impl(
        q, k, v, causal, scale, block_q, block_kv, interpret
    )
    b, s, h, d = q.shape
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _flash_fwd(
    q, k, v, causal, scale, block_q, block_kv,
    bwd_block_q, bwd_block_kv, interpret,
):
    out, lse = _flash_impl(
        q, k, v, causal, scale, block_q, block_kv, interpret
    )
    b, s, h, d = q.shape
    # Residual tags: under jax.checkpoint, a policy that saves
    # "flash_out"/"flash_lse" keeps these across the remat boundary, so
    # the backward replay rebuilds only the (cheap) projections and
    # SKIPS re-running the forward flash kernel — the models' remat
    # mode "flash" (models/llama.py) is built on exactly this.
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    # Separately-named q/k/v residual tags let a policy ALSO pin the
    # attention inputs (skipping the projection/RoPE recompute) at
    # ~2x the memory of flash_out alone.
    q = checkpoint_name(q, "flash_qkv")
    k = checkpoint_name(k, "flash_qkv")
    v = checkpoint_name(v, "flash_qkv")
    return (
        out.reshape(b, h, s, d).transpose(0, 2, 1, 3),
        (q, k, v, out, lse),
    )


def _flash_bwd(
    causal, scale, block_q, block_kv, bwd_block_q, bwd_block_kv,
    interpret, res, g,
):
    # The backward sweep has its own optimum (smaller q blocks pipeline
    # the 5-matmul body better than the forward's fatter tiles).
    block_q, block_kv = bwd_block_q, bwd_block_kv
    q, k, v, out, lse = res
    b, s, h, d = q.shape
    hkv = k.shape[2]
    n_rep = h // hkv

    # Kernels consume the pre-scaled q (matches the saved lse; dk then
    # needs no extra scale and dq scales once at finalize).
    qs = (q.astype(jnp.float32) * scale).astype(q.dtype)
    qr = qs.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    # kv stays at Hkv heads: kernels read the shared head via the same
    # bh // n_rep index map as the forward (no materialized repeat).
    kr = k.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    do = g.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    # delta_i = rowsum(dO_i * O_i) — cheap, fused by XLA.
    delta = (do.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    delta = delta[:, None, :]  # [BH, 1, S] to match the lse layout

    num_q, num_kv = s // block_q, s // block_kv
    # Fused one-pass backward: kv blocks outer, q blocks inner. dk/dv
    # OUTPUTS are per-q-head (grid over B*H) and group-summed below —
    # only they need the n_rep expansion, not the k/v inputs.
    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, ki, qi: (bh, qi, 0))
    kv_in_spec = pl.BlockSpec(
        (1, block_kv, d),
        lambda bh, ki, qi, n_rep=n_rep: (bh // n_rep, ki, 0),
    )
    kv_out_spec = pl.BlockSpec(
        (1, block_kv, d), lambda bh, ki, qi: (bh, ki, 0)
    )
    row_spec = pl.BlockSpec((1, 1, block_q), lambda bh, ki, qi: (bh, 0, qi))
    # The VMEM dq slab scales with S; past the budget (seq ~8k at d=128)
    # fall back to HBM fp32 partials summed outside the kernel (measured
    # ~2% slower at bench shapes; the slab path wins where it fits).
    dq_slab = s * d * 4 <= _DQ_SLAB_VMEM_BYTES
    if dq_slab:
        dq_spec = pl.BlockSpec(
            (1, block_q, d), lambda bh, ki, qi: (bh, qi, 0)
        )
        dq_shape = jax.ShapeDtypeStruct((b * h, s, d), q.dtype)
        dq_scratch = pltpu.VMEM((s, d), jnp.float32)  # whole-head slab
    else:
        dq_spec = pl.BlockSpec(
            (1, 1, block_q, d), lambda bh, ki, qi: (ki, bh, qi, 0)
        )
        dq_shape = jax.ShapeDtypeStruct((num_kv, b * h, s, d), jnp.float32)
        dq_scratch = pltpu.VMEM((8, d), jnp.float32)  # unused dummy
    dq, dk_e, dv_e = pl.pallas_call(
        functools.partial(
            _bwd_kernel, block_q=block_q, block_kv=block_kv, num_q=num_q,
            num_kv=num_kv, scale=scale, causal=causal, dq_slab=dq_slab,
        ),
        grid=(b * h, num_kv, num_q),
        in_specs=[
            q_spec, kv_in_spec, kv_in_spec, q_spec, row_spec, row_spec
        ],
        out_specs=[dq_spec, kv_out_spec, kv_out_spec],
        out_shape=[
            dq_shape,
            jax.ShapeDtypeStruct((b * h, s, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, s, d), v.dtype),
        ],
        scratch_shapes=[
            dq_scratch,
            pltpu.VMEM((block_kv, d), jnp.float32),
            pltpu.VMEM((block_kv, d), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr, do, lse, delta)

    if not dq_slab:
        dq = dq.sum(0).astype(q.dtype)
    dq = dq.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    # Sum each kv group's n_rep expanded gradients back to Hkv heads.
    dk = (
        dk_e.reshape(b, hkv, n_rep, s, d).sum(2).transpose(0, 2, 1, 3)
    ).astype(k.dtype)
    dv = (
        dv_e.reshape(b, hkv, n_rep, s, d).sum(2).transpose(0, 2, 1, 3)
    ).astype(v.dtype)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


# Default tile sizes, tuned on v5e (see flash_attention docstring);
# exported so gating code derives fitted blocks from the SAME value the
# kernel will use (llm/kv_cache.py).
DEFAULT_BLOCK = 1024
# Backward-sweep tiles (fused one-pass kernel), tuned separately on v5e
# at the bench shapes — the 5-matmul body pipelines best with narrower
# q tiles than the forward.
DEFAULT_BWD_BLOCK_Q = 1024
DEFAULT_BWD_BLOCK_KV = 1024


def _fit_block(requested: int, s: int) -> int:
    """Largest block <= requested that divides s (s itself when s fits).
    Prime-ish lengths collapse to tiny blocks — callers that can choose
    another path should gate on the fitted size (see llm/kv_cache.py)."""
    if s <= requested:
        return s
    for d in range(requested, 0, -1):
        if s % d == 0:
            return d
    return 1


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "block_q", "block_kv", "bwd_block_q", "bwd_block_kv",
        "interpret", "scale",
    ),
)
def flash_attention(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,  # [B, S, Hkv, D]
    v: jnp.ndarray,  # [B, S, Hkv, D]
    *,
    causal: bool = True,
    scale: float | None = None,
    # PRECONDITION: post-scale attention scores must stay well inside
    # (-1e9, +inf). The kernel masks with a FINITE -1e9 (so no NaN-guard
    # `where` passes are needed); a real score at or below -1e9 —
    # representable in bf16 up to ~3e38 with pathological/unnormalized
    # activations — would rank BELOW masked positions and silently
    # corrupt the softmax. Normalized transformer activations sit orders
    # of magnitude away from this; interpret=True adds an assertion.
    # DEFAULT_BLOCK (1024/1024) measured fastest on v5e at seq 2048
    # (27ms vs 36ms fwd+bwd for the old 256/512 at B16·H16·D64); blocks
    # clamp to the sequence for short inputs. The fused backward prefers
    # its own (narrower-q) tiles — None inherits the forward blocks.
    block_q: int = DEFAULT_BLOCK,
    block_kv: int = DEFAULT_BLOCK,
    bwd_block_q: int | None = DEFAULT_BWD_BLOCK_Q,
    bwd_block_kv: int | None = DEFAULT_BWD_BLOCK_KV,
    interpret: bool = False,
) -> jnp.ndarray:
    b, s, h, d = q.shape
    hkv = k.shape[2]
    if h % hkv:
        raise ValueError(f"n_heads={h} not divisible by n_kv={hkv}")
    # Largest divisor of the sequence that fits the request — any s
    # works: s <= block keeps one full block (the old fast path);
    # awkward lengths degrade to their largest divisor (prime-ish
    # lengths degrade hard — perf-sensitive callers gate on _fit_block).
    block_q = _fit_block(block_q, s)
    block_kv = _fit_block(block_kv, s)
    bwd_block_q = _fit_block(bwd_block_q or block_q, s)
    bwd_block_kv = _fit_block(bwd_block_kv or block_kv, s)
    # A tiny fitted block (prime-ish seq) means orders-of-magnitude
    # slower Pallas tiles than the MXU-friendly sizes — warn instead of
    # silently cliffing (trace-time only; jit caches per static shape).
    if min(block_q, block_kv) < 128 and s > 128:
        import warnings

        # tpulint: allow(TPU602 reason=once-per-compilation is the intent - the slowdown is a property of the STATIC block sizes, so trace time (one warn per compiled shape, via the jit cache) is exactly the right cadence; per-step emission would spam)
        warnings.warn(
            f"flash_attention: seq={s} only admits blocks "
            f"(q={block_q}, kv={block_kv}) < 128 — expect a severe "
            "slowdown; pad the sequence to a multiple of 128 or use "
            "dense attention for this shape",
            stacklevel=2,
        )
    if scale is None:
        scale = d**-0.5
    if interpret:
        # Debug-mode guard for the finite-mask precondition (see the
        # signature comment). This function is jit-wrapped, so the
        # check rides a host callback (interpret mode is the CPU/debug
        # path — the callback cost is irrelevant there); |scores| is
        # bounded by the product of input maxima.
        bound = (
            jnp.max(jnp.abs(q.astype(jnp.float32)))
            * jnp.max(jnp.abs(k.astype(jnp.float32)))
            * abs(scale)
            * d
        )

        def _host_check(b):
            if float(b) >= 1e8:
                raise AssertionError(
                    f"flash_attention: |scores| can reach {float(b):.3g}"
                    " — within 10x of the -1e9 finite mask (masked "
                    "positions would outrank real ones); normalize the "
                    "inputs or use dense attention"
                )

        jax.debug.callback(_host_check, bound)
    return _flash(
        q, k, v, causal, scale, block_q, block_kv,
        bwd_block_q, bwd_block_kv, interpret,
    )


def make_flash_attention(mesh, batch_axes=("dp", "fsdp"), head_axis="tp"):
    """Build a trainer attention fn running the flash kernel per shard
    under shard_map (batch sharded over the data axes, heads over tp;
    sequence stays local — combine with ring attention for SP). Drop-in
    for ray_tpu.models.llama.forward(attn_fn=...)."""
    from jax.sharding import PartitionSpec as P

    interpret = jax.default_backend() != "tpu"
    spec = P(batch_axes, None, head_axis, None)

    def kernel(q, k, v):
        return flash_attention(q, k, v, interpret=interpret)

    if mesh is None or mesh.size == 1:
        return kernel
    # check_vma=False: pallas_call outputs carry no varying-mesh-axes
    # metadata, which the checker would otherwise require.
    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
