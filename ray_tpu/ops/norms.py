"""RMSNorm, computed in fp32 and cast back — XLA fuses this into the
neighboring matmul's prologue, so no Pallas kernel is needed."""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    orig_dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = x32 * jnp.reciprocal(jnp.sqrt(var + eps))
    out = normed * (1.0 + scale.astype(jnp.float32))
    return out.astype(orig_dtype)
