"""Native (C++) components, built on demand with the system toolchain.

The image has g++/cmake/ninja but no pybind11, so native code exposes a
flat C ABI consumed via ctypes (see native/shmstore/shmstore.cpp). The
first import compiles the shared library into a cache directory; later
imports reuse it keyed by a source hash.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import threading

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
# tpulint: allow(TPU703 reason=build-cache dir is resolved at import time of the native loader — before any config registry exists to consult)
_CACHE = os.environ.get(
    "RAY_TPU_NATIVE_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "ray_tpu", "native"),
)
_lock = threading.Lock()
# out path → Event set when a build attempt for it finishes. The lock
# guards only this dict: the multi-second g++ run happens OUTSIDE the
# critical section, so a cold-cache build can't stall every other
# import-time caller on `_lock` (tpulint TPU201).
_building: dict[str, threading.Event] = {}


class NativeBuildError(RuntimeError):
    pass


def build_library(name: str, sources: list[str], extra_flags: list[str] | None = None) -> str:
    """Compile `sources` (repo-relative) into lib<name>.so; returns path."""
    srcs = [os.path.join(_REPO_ROOT, s) for s in sources]
    h = hashlib.sha1()
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    tag = h.hexdigest()[:16]
    out = os.path.join(_CACHE, f"lib{name}-{tag}.so")
    while not os.path.exists(out):
        with _lock:
            ev = _building.get(out)
            if ev is None:
                ev = _building[out] = threading.Event()
                break  # this thread builds
        # Another thread is building this library: wait for its
        # attempt, then re-check the cache. If it failed, loop around
        # and take our own turn (its exception is its caller's).
        ev.wait()
    else:
        return out
    try:
        os.makedirs(_CACHE, exist_ok=True)
        # Per-process AND per-thread temp name: concurrent cold-cache
        # builds (several worker processes, or two threads racing the
        # event above) must not scribble on one .tmp file (the rename
        # is atomic; last writer wins with identical bytes).
        tmp = f"{out}.tmp{os.getpid()}.{threading.get_ident()}"
        cmd = (
            ["g++", "-O2", "-g", "-fPIC", "-shared", "-std=c++17"]
            + (extra_flags or [])
            + srcs
            + ["-lpthread", "-o", tmp]
        )
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise NativeBuildError(
                f"g++ failed for {name}:\n{proc.stderr[-4000:]}"
            )
        os.rename(tmp, out)
    finally:
        with _lock:
            _building.pop(out, None)
        ev.set()
    return out
