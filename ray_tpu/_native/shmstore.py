"""ctypes binding for the C++ shared-memory object pool
(native/shmstore/shmstore.cpp — the plasma-store equivalent, reference:
src/ray/object_manager/plasma/{store.h,plasma_allocator.h,eviction_policy.h}).

Python-side object layout inside a pool allocation matches the file-store
layout (runtime/object_store.py): header + inband + 64B-aligned buffers,
so `PoolView` hands out zero-copy memoryviews into the pool mapping.
"""

from __future__ import annotations

import ctypes
import errno
import os
import struct
import weakref

from ray_tpu._native import build_library

_HEADER = struct.Struct("<QQI")
_LEN = struct.Struct("<Q")
_MAGIC = 0x52545055_53544F52
_ALIGN = 64
_ID_LEN = 20


def _aligned(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    path = build_library("shmstore", ["native/shmstore/shmstore.cpp"])
    lib = ctypes.CDLL(path)
    lib.shm_pool_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32]
    lib.shm_pool_create.restype = ctypes.c_int
    lib.shm_pool_open.argtypes = [ctypes.c_char_p]
    lib.shm_pool_open.restype = ctypes.c_void_p
    lib.shm_pool_close.argtypes = [ctypes.c_void_p]
    lib.shm_pool_base.argtypes = [ctypes.c_void_p]
    lib.shm_pool_base.restype = ctypes.c_void_p
    lib.shm_pool_capacity.argtypes = [ctypes.c_void_p]
    lib.shm_pool_capacity.restype = ctypes.c_uint64
    lib.shm_pool_used.argtypes = [ctypes.c_void_p]
    lib.shm_pool_used.restype = ctypes.c_uint64
    for fn in ("shm_seal", "shm_contains", "shm_release", "shm_delete", "shm_abort"):
        f = getattr(lib, fn)
        f.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        f.restype = ctypes.c_int
    lib.shm_release_at.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.shm_release_at.restype = ctypes.c_int
    lib.shm_create.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.shm_create.restype = ctypes.c_int
    lib.shm_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.shm_get.restype = ctypes.c_int
    lib.shm_pool_scan.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint32,
    ]
    lib.shm_pool_scan.restype = ctypes.c_int
    _lib = lib
    return lib


def _pad_id(id_bytes: bytes) -> bytes:
    if len(id_bytes) > _ID_LEN:
        raise ValueError("object id too long for pool slot")
    return id_bytes.ljust(_ID_LEN, b"\0")


class _Pin:
    """Holds one pool refcount; drops it when garbage-collected. Keyed
    by the allocation's offset, not its id, so it stays correct if the
    id is deleted and re-created while this reader is still pinned."""

    __slots__ = ("__weakref__",)

    def __init__(self, pool: "ShmPool", abs_off: int):
        weakref.finalize(self, pool._release_at, abs_off)


class PoolView:
    """Zero-copy view into the pool.

    The refcount pin must outlive every consumer of the memory, not just
    this view object: pickle-5 deserialization hands the buffers to numpy
    arrays that alias the pool block. Each buffer is therefore exported
    through a ctypes array that carries the shared `_Pin` — the arrays sit
    on the deserialized values' `.base` chains, so the pin (and the block)
    is released exactly when the last aliasing value is garbage-collected,
    never while one is live. (The plasma client gets the same property from
    its C++ PlasmaBuffer releasing on destruction, reference:
    src/ray/object_manager/plasma/client.h.)
    """

    __slots__ = ("inband", "buffers", "_pin", "__weakref__")

    def __init__(self, pool: "ShmPool", abs_off: int, mv: memoryview):
        magic, inband_len, n_buffers = _HEADER.unpack_from(mv, 0)
        if magic != _MAGIC:
            raise ValueError("corrupt pool object")
        pin = _Pin(pool, abs_off)
        self._pin = pin
        off = _HEADER.size
        lens = []
        for _ in range(n_buffers):
            (length,) = _LEN.unpack_from(mv, off)
            lens.append(length)
            off += _LEN.size
        self.inband = mv[off : off + inband_len]
        off = _aligned(off + inband_len)
        self.buffers = []
        for length in lens:
            self.buffers.append(_pinned_slice(mv, off, length, pin))
            off = _aligned(off + length)


def _pinned_slice(mv: memoryview, off: int, length: int, pin: _Pin):
    """A memoryview of mv[off:off+length] whose exporter (a ctypes array)
    strongly references `pin`, tying the pool refcount to consumer
    lifetime (see PoolView docstring)."""
    if length == 0:
        return memoryview(b"")
    arr = (ctypes.c_char * length).from_buffer(mv, off)
    arr._pin = pin
    return memoryview(arr).cast("B")


class ShmPool:
    """One pool per node; every process maps the same file."""

    def __init__(self, path: str, capacity: int, num_slots: int = 65536):
        lib = _load()
        self._lib = lib
        self.path = path
        rc = lib.shm_pool_create(path.encode(), capacity, num_slots)
        if rc != 0 and rc != -errno.EEXIST:
            raise OSError(-rc, f"shm_pool_create({path}): {os.strerror(-rc)}")
        self._h = lib.shm_pool_open(path.encode())
        if not self._h:
            raise OSError(f"shm_pool_open({path}) failed")
        base = lib.shm_pool_base(self._h)
        cap = lib.shm_pool_capacity(self._h)
        self._mem = memoryview(
            (ctypes.c_char * cap).from_address(base)
        ).cast("B")

    # -- store interface ----------------------------------------------
    def put(self, id_bytes: bytes, data) -> int:
        """`data` is a Serialized (inband + buffers). Returns total bytes,
        0 if the object already exists (immutable double-put no-op)."""
        lib = self._lib
        if not self._h:
            raise ValueError("pool is closed")
        pid = _pad_id(id_bytes)
        header = _HEADER.pack(_MAGIC, len(data.inband), len(data.buffers))
        lens = b"".join(_LEN.pack(len(b)) for b in data.buffers)
        total = _aligned(len(header) + len(lens) + len(data.inband))
        for b in data.buffers:
            total = _aligned(total + len(b))
        total = max(total, 1)
        off = ctypes.c_uint64()
        rc = lib.shm_create(self._h, pid, total, ctypes.byref(off))
        if rc == -errno.EEXIST:
            return 0
        if rc != 0:
            raise MemoryError(
                f"pool create failed ({os.strerror(-rc)}): {total} bytes, "
                f"{self.used_bytes()}/{len(self._mem)} used"
            )
        try:
            m = self._mem
            base = off.value
            o = 0
            for part in (header, lens, bytes(data.inband)):
                m[base + o : base + o + len(part)] = part
                o += len(part)
            o = _aligned(o)
            for b in data.buffers:
                bb = b if isinstance(b, (bytes, memoryview)) else bytes(b)
                m[base + o : base + o + len(bb)] = bb
                o = _aligned(o + len(bb))
        except BaseException:
            if lib.shm_abort(self._h, pid) == -errno.ENOENT:
                # A concurrent delete zombified the in-creation slot
                # (find_slot skips zombies): drop the creator's pin by
                # offset so the block frees.
                lib.shm_release_at(self._h, off.value)
            raise
        rc = lib.shm_seal(self._h, pid)
        if rc == -errno.ENOENT:
            # Deleted while creating: equivalent to a successful put
            # immediately followed by the delete. Release the creator's
            # pin (frees the zombie block) and report success.
            lib.shm_release_at(self._h, off.value)
            return total
        if rc != 0:
            raise OSError(f"seal failed: {os.strerror(-rc)}")
        return total

    def get(self, id_bytes: bytes) -> PoolView | None:
        lib = self._lib
        if not self._h:
            return None
        pid = _pad_id(id_bytes)
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = lib.shm_get(self._h, pid, ctypes.byref(off), ctypes.byref(size))
        if rc == -errno.ENOENT:
            return None
        if rc != 0:
            raise OSError(f"get failed: {os.strerror(-rc)}")
        mv = self._mem[off.value : off.value + size.value]
        return PoolView(self, off.value, mv)

    def contains(self, id_bytes: bytes) -> bool:
        if not self._h:
            return False
        return bool(self._lib.shm_contains(self._h, _pad_id(id_bytes)))

    def delete(self, id_bytes: bytes) -> None:
        if self._h:
            self._lib.shm_delete(self._h, _pad_id(id_bytes))

    def _release_at(self, abs_off: int) -> None:
        try:
            if self._h:
                self._lib.shm_release_at(self._h, abs_off)
        # tpulint: allow(broad-except reason=runs from buffer-finalizer callbacks during interpreter teardown where the pool handle may already be freed; raising would abort unrelated GC)
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    def used_bytes(self) -> int:
        return self._lib.shm_pool_used(self._h) if self._h else 0

    def capacity_bytes(self) -> int:
        return self._lib.shm_pool_capacity(self._h) if self._h else 0

    def scan(self, max_entries: int = 8192) -> list[tuple[bytes, int, int]]:
        """(id_bytes, size, lru_tick) for sealed, unpinned objects —
        the spill loop's candidate list, coldest-first after sorting."""
        if not self._h:
            return []
        ids = (ctypes.c_uint8 * (max_entries * _ID_LEN))()
        sizes = (ctypes.c_uint64 * max_entries)()
        lru = (ctypes.c_uint64 * max_entries)()
        n = self._lib.shm_pool_scan(
            self._h, ids, sizes, lru, max_entries
        )
        out = []
        raw = bytes(ids)
        for i in range(max(n, 0)):
            out.append(
                (raw[i * _ID_LEN : (i + 1) * _ID_LEN], sizes[i], lru[i])
            )
        return out

    def close(self) -> None:
        # Deliberately do NOT munmap: PoolViews hand out zero-copy
        # memoryviews into the mapping, and late finalizers (or user code
        # holding arrays) would fault on a torn-down map. The mapping and
        # fd live until process exit — same lifetime plasma clients give
        # their mmaps (reference: plasma client keeps maps for the
        # connection lifetime).
        self._h = None

    def destroy(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass
