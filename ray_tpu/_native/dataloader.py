"""ctypes binding for the C++ token data loader
(native/dataloader/dataloader.cpp): mmap'd token corpus → shuffled
[batch, seq+1] uint32 batches, with a background prefetch thread."""

from __future__ import annotations

import ctypes

import numpy as np

from ray_tpu._native import build_library

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    path = build_library("dataloader", ["native/dataloader/dataloader.cpp"])
    lib = ctypes.CDLL(path)
    lib.dl_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_uint64]
    lib.dl_open.restype = ctypes.c_void_p
    lib.dl_close.argtypes = [ctypes.c_void_p]
    lib.dl_num_windows.argtypes = [ctypes.c_void_p]
    lib.dl_num_windows.restype = ctypes.c_uint64
    lib.dl_shuffle.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.dl_shuffle.restype = ctypes.c_int
    lib.dl_set_shard.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64
    ]
    lib.dl_set_shard.restype = ctypes.c_int
    lib.dl_fill.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.dl_fill.restype = ctypes.c_uint64
    lib.dl_prefetch_start.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.dl_prefetch_start.restype = ctypes.c_int
    lib.dl_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint32)
    ]
    lib.dl_next.restype = ctypes.c_uint64
    lib.dl_prefetch_stop.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class NativeTokenLoader:
    """Thin handle over the C++ loader; see ray_tpu.train.dataloader for
    the user-facing iterator."""

    def __init__(self, path: str, window: int, dtype_bytes: int = 4):
        lib = _load()
        self._lib = lib
        self._h = lib.dl_open(path.encode(), dtype_bytes, window)
        if not self._h:
            raise OSError(f"dl_open({path!r}) failed")
        self.window = window
        self._prefetching = False

    @property
    def num_windows(self) -> int:
        return self._lib.dl_num_windows(self._h)

    def shuffle(self, seed: int) -> None:
        if self._lib.dl_shuffle(self._h, seed) != 0:
            raise RuntimeError("cannot shuffle while prefetching")

    def set_shard(self, rank: int, world: int) -> None:
        if self._lib.dl_set_shard(self._h, rank, world) != 0:
            raise RuntimeError("cannot re-shard while prefetching")

    def fill(self, start: int, batch: int) -> np.ndarray:
        out = np.empty((batch, self.window), np.uint32)
        rows = self._lib.dl_fill(
            self._h, start, batch,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        )
        return out[:rows]

    def prefetch_start(self, batch: int) -> None:
        rc = self._lib.dl_prefetch_start(self._h, batch)
        if rc != 0:
            raise RuntimeError(f"prefetch already running ({rc})")
        self._batch = batch
        self._prefetching = True

    def next(self) -> np.ndarray:
        out = np.empty((self._batch, self.window), np.uint32)
        rows = self._lib.dl_next(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))
        )
        return out[:rows]

    def prefetch_stop(self) -> None:
        if self._prefetching:
            self._lib.dl_prefetch_stop(self._h)
            self._prefetching = False

    def close(self) -> None:
        if self._h:
            self._lib.dl_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        # tpulint: allow(broad-except reason=__del__ during interpreter teardown must never raise; the ctypes handle may already be torn down and there is no logger left to tell)
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass
