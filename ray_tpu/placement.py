"""Placement groups: gang-reserved resource bundles.

Public surface mirrors the reference (reference:
python/ray/util/placement_group.py — placement_group(), ready(),
remove_placement_group(); strategies PACK/SPREAD/STRICT_*), including the
TPU twist: a whole-slice reservation helper in the spirit of
ray.util.tpu.SlicePlacementGroup (util/tpu.py:223) that makes an
ICI-connected slice the bundle unit.
"""

from __future__ import annotations

from typing import Sequence

from ray_tpu._private.ids import ActorID


class PlacementGroup:
    def __init__(
        self,
        pg_id: str,
        bundles: list[dict],
        strategy: str,
        node_infos: list[dict],
    ):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.node_infos = node_infos  # per-bundle {node_id, addr}

    def bundle_node_addr(self, index: int) -> str:
        return self.node_infos[index]["addr"]

    def ready(self) -> bool:
        return True  # creation is synchronous in this runtime

    def __reduce__(self):
        return (
            PlacementGroup,
            (self.id, self.bundles, self.strategy, self.node_infos),
        )

    def __repr__(self):
        return f"PlacementGroup({self.id[:8]}…, {len(self.bundles)} bundles)"


def placement_group(
    bundles: Sequence[dict],
    strategy: str = "PACK",
    name: str | None = None,
) -> PlacementGroup:
    import ray_tpu.api as api

    rt = api._runtime
    pg_id = ActorID.random().hex()
    reply = rt.run(
        rt.core.head.call(
            "create_placement_group",
            pg_id=pg_id,
            bundles=[dict(b) for b in bundles],
            strategy=strategy,
        )
    )
    if not reply.get("ok"):
        raise ValueError(
            f"placement group creation failed: {reply.get('error')}"
        )
    return PlacementGroup(pg_id, list(bundles), strategy, reply["nodes"])


def remove_placement_group(pg: PlacementGroup) -> None:
    import ray_tpu.api as api

    rt = api._runtime
    rt.run(rt.core.head.call("remove_placement_group", pg_id=pg.id))


def slice_placement_group(
    num_hosts: int, chips_per_host: int = 4, strategy: str = "STRICT_SPREAD"
) -> PlacementGroup:
    """Reserve a TPU slice as one gang: one bundle per host, each holding
    that host's chips (reference: ray.util.tpu.slice_placement_group
    util/tpu.py:458 approximates this with label selectors)."""
    return placement_group(
        [{"TPU": float(chips_per_host), "CPU": 1.0}] * num_hosts,
        strategy=strategy,
    )


def cross_slice_placement_group(
    num_bundles: int, bundle: "dict | None" = None
) -> PlacementGroup:
    """Reserve ``num_bundles`` bundles on ``num_bundles`` DISTINCT
    slices (strategy ``STRICT_SPREAD_SLICES``): the fault-domain dual of
    :func:`slice_placement_group`. A whole-slice preemption then takes
    at most ONE bundle — the placement shape for checkpoint replica
    holders, replicated serve deployments, and anything else that must
    survive a slice going away as a unit. Nodes without a ``slice``
    label count as their own singleton fault domain. Fails when the
    cluster has fewer distinct slices than bundles."""
    return placement_group(
        [dict(bundle or {"CPU": 1.0})] * num_bundles,
        strategy="STRICT_SPREAD_SLICES",
    )
