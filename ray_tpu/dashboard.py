"""Dashboard-lite: an HTTP window onto cluster state.

Reference: python/ray/dashboard/ (aiohttp head process + React client +
per-node agents). TPU-native scope: the data pipeline already terminates
at the head (task events, metrics, node/actor tables — SURVEY.md §5), so
the dashboard is a thin stdlib HTTP server over the state API: JSON
endpoints for machines, a Prometheus endpoint for scrapers, and a small
HTML status page for humans.
"""

from __future__ import annotations

import html
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ray_tpu.util import state

_ROUTES = {}


def _route(path):
    def deco(fn):
        _ROUTES[path] = fn
        return fn

    return deco


@_route("/api/nodes")
def _nodes():
    return state.list_nodes()


@_route("/api/actors")
def _actors():
    return state.list_actors()


@_route("/api/tasks")
def _tasks():
    return state.list_tasks(limit=1000)


@_route("/api/task_summary")
def _task_summary():
    return state.summarize_tasks()


@_route("/api/placement_groups")
def _pgs():
    return state.list_placement_groups()


@_route("/api/jobs")
def _jobs():
    from ray_tpu.job import JobSubmissionClient

    return JobSubmissionClient().list_jobs()


@_route("/api/logs")
def _logs():
    return state.list_worker_logs()


def _index_html() -> str:
    nodes = state.list_nodes()
    actors = state.list_actors()
    summary = state.summarize_tasks()
    rows = "".join(
        f"<tr><td>{html.escape(n['node_id'][:12])}</td>"
        f"<td>{html.escape(n['addr'])}</td>"
        f"<td>{html.escape(json.dumps(n['resources']))}</td>"
        f"<td>{html.escape(json.dumps(n['available']))}</td></tr>"
        for n in nodes
    )
    alive = sum(1 for a in actors if a["state"] == "ALIVE")
    return f"""<!doctype html><html><head><title>ray_tpu dashboard</title>
<style>body{{font-family:monospace;margin:2em}}table{{border-collapse:collapse}}
td,th{{border:1px solid #999;padding:4px 8px}}</style></head><body>
<h2>ray_tpu cluster</h2>
<p>nodes: {len(nodes)} &middot; actors alive: {alive}/{len(actors)}
&middot; tasks: {html.escape(json.dumps(summary))}</p>
<table><tr><th>node</th><th>addr</th><th>total</th><th>available</th></tr>
{rows}</table>
<p>endpoints: /api/nodes /api/actors /api/tasks /api/task_summary
/api/placement_groups /api/jobs /metrics</p>
</body></html>"""


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 - stdlib API
        try:
            self.path = self.path.split("?", 1)[0]  # drop query strings
            if self.path == "/" or self.path == "/index.html":
                body = _index_html().encode()
                ctype = "text/html"
            elif self.path == "/metrics":
                body = state.prometheus_metrics().encode()
                ctype = "text/plain; version=0.0.4"
            elif self.path in _ROUTES:
                body = json.dumps(_ROUTES[self.path]()).encode()
                ctype = "application/json"
            elif self.path.startswith("/api/logs/"):
                text = state.read_worker_log(
                    self.path[len("/api/logs/"):]
                )
                if text is None:
                    self.send_error(404)
                    return
                body = text.encode()
                ctype = "text/plain"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except BrokenPipeError:
            pass
        except Exception as e:  # noqa: BLE001
            self.send_error(500, explain=repr(e))

    def log_message(self, *args):  # silence per-request stderr noise
        pass


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="ray_tpu_dashboard",
            daemon=True,
        )

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> str:
        self._thread.start()
        return self.url

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


def start_dashboard(host: str = "127.0.0.1", port: int = 0) -> Dashboard:
    """Serve the dashboard from this (driver) process; returns the
    running Dashboard (use .url)."""
    dash = Dashboard(host, port)
    dash.start()
    return dash
