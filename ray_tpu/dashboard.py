"""Dashboard-lite: an HTTP window onto cluster state.

Reference: python/ray/dashboard/ (aiohttp head process + React client +
per-node agents). TPU-native scope: the data pipeline already terminates
at the head (task events, metrics, node/actor tables — SURVEY.md §5), so
the dashboard is a thin stdlib HTTP server over the state API: JSON
endpoints for machines, a Prometheus endpoint for scrapers, and a small
HTML status page for humans.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ray_tpu.util import state

_ROUTES = {}


def _route(path):
    def deco(fn):
        _ROUTES[path] = fn
        return fn

    return deco


@_route("/api/nodes")
def _nodes():
    return state.list_nodes()


@_route("/api/actors")
def _actors():
    return state.list_actors()


@_route("/api/tasks")
def _tasks():
    return state.list_tasks(limit=1000)


@_route("/api/task_summary")
def _task_summary():
    return state.summarize_tasks()


@_route("/api/placement_groups")
def _pgs():
    return state.list_placement_groups()


@_route("/api/train")
def _train():
    """Per-train-job goodput/MFU (head train-step accounting), incl.
    time lost to elastic attempt restarts."""
    return state.train_stats()


@_route("/api/tune")
def _tune():
    """Sweep-engine ledger (head journaled sweeps table): per-trial
    gang states, rung stops, PBT forks, preemption migrations, with
    each trial's train-job goodput/loss row joined in."""
    return state.sweep_stats()


@_route("/api/serve")
def _serve():
    """Per-deployment serve SLO ledger (head serve:ingress-span
    accounting): TTFT/latency percentiles over the sliding window,
    attainment vs the SLO targets, and the burn-rate alert state."""
    return state.serve_stats()


@_route("/api/memory")
def _memory():
    """Head device-memory ledger (mem:sample span accounting): per-node
    used/peak/capacity/headroom with per-subsystem byte attribution and
    the headroom alert state, plus per-job peaks."""
    return state.mem_stats()


@_route("/api/profile")
def _profile():
    """Compiled-program profiler ledger (profile:step span
    accounting): per-job MFU decomposition shares, the dominant
    non-compute gap, and the regression-sentinel state with its
    journaled per-signature fingerprints."""
    return state.profile_stats()


@_route("/api/head")
def _head():
    """Head control-plane load: telemetry fold-queue depth, shed
    counter, overload alert, pubsub coalescing counters, and journal
    size/compaction state."""
    return state.head_stats()


@_route("/api/checkpoints")
def _checkpoints():
    """In-cluster shard-store checkpoints: per-run steps with
    completeness, dedup'd byte counts, and replica health."""
    return state.list_checkpoints()


_job_client = None
_job_client_lock = threading.Lock()


def _jobs_client():
    """One shared client so supervisor handles survive across requests
    (reference: JobHead keeps one JobManager, job_head.py:208)."""
    global _job_client
    with _job_client_lock:
        if _job_client is None:
            from ray_tpu.job import JobSubmissionClient

            _job_client = JobSubmissionClient()
        return _job_client


@_route("/api/jobs")
def _jobs():
    return _jobs_client().list_jobs()


@_route("/api/logs")
def _logs():
    return state.list_worker_logs()


@_route("/api/usage")
def _usage():
    from ray_tpu._private import usage

    return usage.usage_stats()


@_route("/api/cluster")
def _cluster():
    """One-call overview for the UI: node/actor/task rollups plus
    per-resource utilization."""
    nodes = state.list_nodes()
    actors = state.list_actors()
    util: dict[str, dict] = {}
    for n in nodes:
        for k, total in n["resources"].items():
            u = util.setdefault(k, {"total": 0.0, "available": 0.0})
            u["total"] += total
            u["available"] += n["available"].get(k, 0)
    return {
        "nodes": len(nodes),
        "actors_alive": sum(1 for a in actors if a["state"] == "ALIVE"),
        "actors_total": len(actors),
        "tasks": state.summarize_tasks(),
        "utilization": util,
    }


# Self-contained single-page UI (reference: the React dashboard client,
# dashboard/client/src/App.tsx — here a zero-build static page polling
# the same JSON endpoints: overview, nodes with per-node agent links,
# actors, tasks, placement groups, jobs, logs with inline viewer).
_SPA = """<!doctype html><html><head><meta charset="utf-8">
<title>ray_tpu dashboard</title><style>
:root{--bg:#111418;--fg:#e6e6e6;--mut:#9aa4ad;--card:#1b2026;--acc:#4fc3f7;
--ok:#66bb6a;--bad:#ef5350}
body{font:13px/1.5 ui-monospace,Menlo,monospace;background:var(--bg);
color:var(--fg);margin:0}
header{display:flex;gap:1.5em;align-items:baseline;padding:.8em 1.2em;
background:var(--card);border-bottom:1px solid #2a323b}
h1{font-size:15px;margin:0;color:var(--acc)}
nav a{color:var(--mut);margin-right:1em;cursor:pointer;text-decoration:none}
nav a.on{color:var(--fg);border-bottom:2px solid var(--acc)}
main{padding:1em 1.2em}
table{border-collapse:collapse;width:100%;margin-top:.6em}
td,th{border-bottom:1px solid #2a323b;padding:4px 8px;text-align:left;
white-space:nowrap}
th{color:var(--mut);font-weight:normal}
.cards{display:flex;gap:1em;flex-wrap:wrap}
.card{background:var(--card);border-radius:6px;padding:.8em 1.2em;min-width:9em}
.card b{display:block;font-size:20px}
.bar{background:#2a323b;border-radius:3px;height:8px;min-width:8em}
.bar i{display:block;height:8px;border-radius:3px;background:var(--acc)}
.ok{color:var(--ok)}.bad{color:var(--bad)}.mut{color:var(--mut)}
pre{background:var(--card);padding:1em;overflow:auto;max-height:60vh}
a{color:var(--acc)}</style></head><body>
<header><h1>ray_tpu</h1><nav id="nav"></nav>
<span class="mut" id="ts"></span></header><main id="main">loading…</main>
<script>
const TABS=["overview","nodes","actors","tasks","placement groups","jobs","logs"];
let tab=location.hash.slice(1)||"overview", logWid=null;
const $=(h)=>{document.getElementById("main").innerHTML=h};
const esc=(s)=>String(s).replace(/[&<>"']/g,c=>({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
const get=async(p)=>(await fetch(p)).json();
function nav(){document.getElementById("nav").innerHTML=TABS.map(t=>
 `<a class="${t===tab?"on":""}" href="#${t}">${t}</a>`).join("")}
window.onhashchange=()=>{tab=location.hash.slice(1)||"overview";logWid=null;draw()};
function bar(used,total){const p=total?Math.round(100*used/total):0;
 return `<div class="bar" title="${p}%"><i style="width:${p}%"></i></div>`}
async function draw(){nav();
 document.getElementById("ts").textContent=new Date().toLocaleTimeString();
 try{
 if(tab==="overview"){const c=await get("/api/cluster");
  let cards=`<div class="card">nodes<b>${c.nodes}</b></div>
   <div class="card">actors<b>${c.actors_alive}<span class="mut">/${c.actors_total}</span></b></div>`;
  for(const[st,n]of Object.entries(c.tasks||{}))
   cards+=`<div class="card">${esc(st.toLowerCase())}<b>${n}</b></div>`;
  let rows=Object.entries(c.utilization).map(([k,u])=>{const used=u.total-u.available;
   return `<tr><td>${esc(k)}</td><td>${used.toFixed(1)}/${u.total.toFixed(1)}</td>
    <td>${bar(used,u.total)}</td></tr>`}).join("");
  $(`<div class="cards">${cards}</div>
   <table><tr><th>resource</th><th>used/total</th><th></th></tr>${rows}</table>`)}
 else if(tab==="nodes"){const ns=await get("/api/nodes");
  $(`<table><tr><th>node</th><th>addr</th><th>agent</th><th>total</th>
   <th>available</th><th>labels</th></tr>`+ns.map(n=>
   `<tr><td>${esc(n.node_id.slice(0,12))}</td><td>${esc(n.addr)}</td>
   <td>${n.agent_addr?(n.agent_addr.startsWith("127.")||n.agent_addr.startsWith("localhost")?esc(n.agent_addr)+" (loopback)":`<a href="http://${esc(n.agent_addr)}/api/stats">${esc(n.agent_addr)}</a>`):"—"}</td>
   <td>${esc(JSON.stringify(n.resources))}</td>
   <td>${esc(JSON.stringify(n.available))}</td>
   <td class="mut">${esc(JSON.stringify(n.labels||{}))}</td></tr>`).join("")+"</table>")}
 else if(tab==="actors"){const as=await get("/api/actors");
  $(`<table><tr><th>actor</th><th>class</th><th>name</th><th>state</th>
   <th>node</th></tr>`+as.map(a=>
   `<tr><td>${esc(a.actor_id.slice(0,12))}</td><td>${esc(a.class_name||"")}</td>
   <td>${esc(a.name||"")}</td>
   <td class="${a.state==="ALIVE"?"ok":"bad"}">${esc(a.state)}</td>
   <td class="mut">${esc((a.node_id||"").slice(0,12))}</td></tr>`).join("")+"</table>")}
 else if(tab==="tasks"){const ts=await get("/api/tasks");
  $(`<table><tr><th>task</th><th>name</th><th>state</th><th>kind</th>
   <th>duration</th></tr>`+ts.slice(0,500).map(t=>
   `<tr><td>${esc((t.task_id||"").slice(0,12))}</td><td>${esc(t.name||"")}</td>
   <td class="${t.state==="FAILED"?"bad":""}">${esc(t.state||"")}</td>
   <td class="mut">${esc(t.kind||"")}</td>
   <td>${t.duration_s!=null?esc(t.duration_s.toFixed?t.duration_s.toFixed(3):t.duration_s)+"s":""}</td></tr>`).join("")+"</table>")}
 else if(tab==="placement groups"){const ps=await get("/api/placement_groups");
  $("<pre>"+esc(JSON.stringify(ps,null,2))+"</pre>")}
 else if(tab==="jobs"){const js=await get("/api/jobs");
  $(`<p><input id="ep" placeholder="entrypoint command" size="60">
   <button id="sub">submit</button></p>
   <table><tr><th>job</th><th>entrypoint</th><th>status</th><th></th></tr>`+
   js.map(j=>`<tr><td>${esc(j.job_id)}</td>
   <td class="mut">${esc(j.entrypoint||"")}</td>
   <td class="${j.status==="FAILED"?"bad":j.status==="SUCCEEDED"?"ok":""}">${esc(j.status)}</td>
   <td>${j.status==="RUNNING"?`<a href="#jobs" class="jstop" data-jid="${esc(j.job_id)}">stop</a>`:""}</td></tr>`).join("")+"</table>");
  document.getElementById("sub").onclick=async()=>{
   const ep=document.getElementById("ep").value;
   if(ep){await fetch("/api/jobs",{method:"POST",
    body:JSON.stringify({entrypoint:ep})});draw()}};
  document.querySelectorAll(".jstop").forEach(a=>a.onclick=async()=>{
   await fetch("/api/jobs/"+a.dataset.jid+"/stop",{method:"POST"});
   draw();return false})}
 else if(tab==="logs"){
  if(logWid){const r=await fetch("/api/logs/"+logWid);
   $(`<p><a href="#logs" onclick="logWid=null;draw()">&larr; back</a>
    worker ${esc(logWid)}</p><pre>${esc(await r.text())}</pre>`)}
  else{const ls=await get("/api/logs");
   $(`<table><tr><th>worker</th><th>node</th><th>size</th><th>status</th></tr>`+
    ls.map(l=>`<tr><td><a href="#logs" class="wlog" data-wid="${esc(l.worker_id)}">
    ${esc(l.worker_id)}</a></td><td class="mut">${esc((l.node_id||"").slice(0,12))}</td>
    <td>${l.size}</td><td class="${l.alive?"ok":"bad"}">${l.alive?"alive":"dead"}</td></tr>`).join("")+"</table>");
   document.querySelectorAll(".wlog").forEach(a=>a.onclick=()=>{logWid=a.dataset.wid;draw();return false})}}
 }catch(e){$(`<p class="bad">fetch failed: ${esc(e)}</p>`)}
}
draw();setInterval(()=>{if(!logWid)draw()},2000);
</script></body></html>"""


def _index_html() -> str:
    return _SPA


class _Handler(BaseHTTPRequestHandler):
    def _reply(self, body: bytes, ctype: str, code: int = 200):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_json(self, obj, code: int = 200):
        self._reply(json.dumps(obj).encode(), "application/json", code)

    def _job_subpath(self) -> tuple[str, str] | None:
        """Split /api/jobs/<id>[/logs|/stop] → (job_id, action)."""
        if not self.path.startswith("/api/jobs/"):
            return None
        rest = self.path[len("/api/jobs/"):].strip("/")
        if not rest:
            return None
        job_id, _, action = rest.partition("/")
        return job_id, action

    def do_GET(self):  # noqa: N802 - stdlib API
        try:
            self.path = self.path.split("?", 1)[0]  # drop query strings
            if self.path == "/" or self.path == "/index.html":
                body = _index_html().encode()
                ctype = "text/html"
            elif self.path == "/metrics":
                body = state.prometheus_metrics().encode()
                ctype = "text/plain; version=0.0.4"
            elif self.path in _ROUTES:
                body = json.dumps(_ROUTES[self.path]()).encode()
                ctype = "application/json"
            elif self.path.startswith("/api/jobs/"):
                self._job_get()
                return
            elif self.path.startswith("/api/logs/"):
                text = state.read_worker_log(
                    self.path[len("/api/logs/"):]
                )
                if text is None:
                    self.send_error(404)
                    return
                body = text.encode()
                ctype = "text/plain"
            else:
                self.send_error(404)
                return
            self._reply(body, ctype)
        except BrokenPipeError:
            pass
        # tpulint: allow(broad-except reason=the handler failure is returned to the HTTP client as the 500 explain body - nothing is swallowed)
        except Exception as e:  # noqa: BLE001
            self.send_error(500, explain=repr(e))

    # ----------------------------------------------------- job REST API
    # (reference: dashboard/modules/job/job_head.py:208 JobHead —
    # POST /api/jobs/, GET /api/jobs/{id}, GET /api/jobs/{id}/logs,
    # POST /api/jobs/{id}/stop, DELETE /api/jobs/{id}; same shape here
    # so the SPA and external CI can drive jobs with plain HTTP.)

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    def _job_or_404(self, job_id: str) -> str | None:
        """One status RPC doubles as the existence check (UNKNOWN means
        no record anywhere) — list_jobs() here would cost a supervisor
        round-trip per RUNNING job just for membership."""
        status = _jobs_client().get_job_status(job_id)
        if status == "UNKNOWN":
            self._reply_json({"error": f"job {job_id!r} not found"}, 404)
            return None
        return status

    def _job_get(self):
        sub = self._job_subpath()
        if sub is None:
            self.send_error(404)
            return
        job_id, action = sub
        status = self._job_or_404(job_id)
        if status is None:
            return
        if action == "logs":
            self._reply(
                _jobs_client().get_job_logs(job_id).encode(), "text/plain"
            )
        elif action == "":
            self._reply_json({"job_id": job_id, "status": status})
        else:
            self.send_error(404)

    def do_POST(self):  # noqa: N802 - stdlib API
        try:
            self.path = self.path.split("?", 1)[0]
            client = _jobs_client()
            if self.path in ("/api/jobs", "/api/jobs/"):
                try:
                    req = json.loads(self._read_body() or b"{}")
                    entrypoint = req["entrypoint"]
                # TypeError: valid JSON that isn't an object ('[1]').
                except (ValueError, KeyError, TypeError) as e:
                    self._reply_json(
                        {"error": f"bad submit request: {e!r}"}, 400
                    )
                    return
                job_id = client.submit_job(
                    entrypoint=entrypoint,
                    submission_id=req.get("submission_id"),
                    runtime_env=req.get("runtime_env"),
                )
                self._reply_json({"job_id": job_id})
                return
            sub = self._job_subpath()
            if sub and sub[1] == "stop":
                if self._job_or_404(sub[0]) is None:
                    return
                stopped = client.stop_job(sub[0])
                self._reply_json({"stopped": stopped})
                return
            self.send_error(404)
        except BrokenPipeError:
            pass
        # tpulint: allow(broad-except reason=the handler failure is returned to the HTTP client as the 500 explain body - nothing is swallowed)
        except Exception as e:  # noqa: BLE001
            self.send_error(500, explain=repr(e))

    def do_DELETE(self):  # noqa: N802 - stdlib API
        try:
            self.path = self.path.split("?", 1)[0]
            sub = self._job_subpath()
            if sub and sub[1] == "":
                if self._job_or_404(sub[0]) is None:
                    return
                try:
                    deleted = _jobs_client().delete_job(sub[0])
                except RuntimeError as e:  # still RUNNING
                    self._reply_json({"error": str(e)}, 400)
                    return
                self._reply_json({"deleted": deleted})
                return
            self.send_error(404)
        except BrokenPipeError:
            pass
        # tpulint: allow(broad-except reason=the handler failure is returned to the HTTP client as the 500 explain body - nothing is swallowed)
        except Exception as e:  # noqa: BLE001
            self.send_error(500, explain=repr(e))

    def log_message(self, *args):  # silence per-request stderr noise
        pass


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="ray_tpu_dashboard",
            daemon=True,
        )

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> str:
        self._thread.start()
        return self.url

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


def start_dashboard(host: str = "127.0.0.1", port: int = 0) -> Dashboard:
    """Serve the dashboard from this (driver) process; returns the
    running Dashboard (use .url)."""
    dash = Dashboard(host, port)
    dash.start()
    return dash
