"""BigQuery datasource over the REST v2 API.

Reference surface: python/ray/data read_bigquery (the reference's
datasource wraps google-cloud-bigquery). This implementation speaks
the jobs.query REST endpoint directly through the same authorized
transport the GKE autoscaler provider uses (metadata-server /
GOOGLE_OAUTH_ACCESS_TOKEN bearer tokens, 401-retry), so it needs no
client library — and tests drive it with the provider's
RecordedTransport fixtures (zero-egress CI).

Plan shape: ONE read task that paginates jobs.query →
getQueryResults. (The reference parallelizes via the BigQuery Storage
API's split streams; the REST surface is paging-only, so the read is
one task and downstream ops re-parallelize via repartition.)
"""

from __future__ import annotations

import numpy as np

_BQ = "https://bigquery.googleapis.com/bigquery/v2"
_PAGE_ROWS = 10000


def _convert(value, bq_type: str):
    if value is None:
        return None
    t = bq_type.upper()
    if t in ("INTEGER", "INT64"):
        return int(value)
    if t in ("FLOAT", "FLOAT64", "NUMERIC", "BIGNUMERIC"):
        return float(value)
    if t in ("BOOLEAN", "BOOL"):
        return value in (True, "true", "TRUE", "True")
    return value


class _BigQueryRead:
    def __init__(self, project: str, query: str, transport=None):
        self.project = project
        self.query = query
        self.transport = transport

    def _http(self):
        if self.transport is not None:
            return self.transport
        from ray_tpu.autoscaler.gcp import GcpTransport

        return GcpTransport()

    def __call__(self):
        http = self._http()
        url = f"{_BQ}/projects/{self.project}/queries"
        reply = http.request(
            "POST",
            url,
            {
                "query": self.query,
                "useLegacySql": False,
                "maxResults": _PAGE_ROWS,
            },
        )
        if not reply.get("jobComplete", True):
            raise RuntimeError(
                "bigquery job did not complete within the synchronous "
                f"window: {reply.get('jobReference')}"
            )
        fields = reply.get("schema", {}).get("fields", [])
        names = [f["name"] for f in fields]
        types = [f.get("type", "STRING") for f in fields]
        columns: "dict[str, list]" = {n: [] for n in names}

        def absorb(rows):
            for row in rows:
                for (name, typ, cell) in zip(
                    names, types, row.get("f", [])
                ):
                    columns[name].append(_convert(cell.get("v"), typ))

        absorb(reply.get("rows", []))
        job_id = reply.get("jobReference", {}).get("jobId")
        token = reply.get("pageToken")
        while token:
            page = http.request(
                "GET",
                f"{url}/{job_id}?pageToken={token}"
                f"&maxResults={_PAGE_ROWS}",
            )
            absorb(page.get("rows", []))
            token = page.get("pageToken")
        return {n: np.asarray(v) for n, v in columns.items()}


def bigquery_tasks(
    *,
    project: str,
    query: "str | None" = None,
    dataset: "str | None" = None,
    transport=None,
) -> list:
    if (query is None) == (dataset is None):
        raise ValueError(
            "read_bigquery takes exactly one of query= or dataset="
        )
    if dataset is not None:
        if "." not in dataset:
            raise ValueError(
                "dataset must be 'dataset.table' (got "
                f"{dataset!r})"
            )
        query = f"SELECT * FROM `{project}.{dataset}`"
    return [_BigQueryRead(project, query, transport=transport)]
